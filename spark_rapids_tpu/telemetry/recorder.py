"""Incident flight recorder: a fixed-size ring of recent engine events,
dumped as a schema-validated JSONL incident file when something terminal
happens.

The query profiler (utils/spans.py) is post-hoc: it exports when a query
FINISHES. The failures that need explaining most — terminal OOM after
the spill framework gave up, a deadline expiry deep in a retry loop, an
admission-rejection storm under overload, a fault-injected terminal
error — are exactly the ones where the query never finishes, so the
profile never lands. The recorder is the black box for those: seams feed
it tiny events continuously (query begin/end, admission, spill, shuffle
retry, OOM), the ring keeps the most recent `capacity`, and `dump()`
writes them with an incident header record that
`spans.validate_record` accepts (type `incident` + type `event`, schema
v2), so the same report tooling reads crash evidence and profiles.

Cost contract: when telemetry is off the recorder object does not exist
(the facade's `flight()` is one module-global check). When on, `record`
takes one small lock, writes one preallocated slot, allocates nothing
but the attrs tuple the caller already built. Dumps are rate-limited per
reason so an OOM loop cannot flood the incident directory.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder"]

_DUMP_MIN_INTERVAL_S = 5.0


class FlightRecorder:
    def __init__(self, capacity: int = 2048, dump_dir: str = "",
                 reject_storm_threshold: int = 8,
                 reject_storm_window_s: float = 10.0):
        self.capacity = max(int(capacity), 16)
        self.dump_dir = dump_dir
        self.reject_storm_threshold = reject_storm_threshold
        self.reject_storm_window_s = reject_storm_window_s
        self._mu = threading.Lock()
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._seq = 0
        self._reject_ts: List[float] = []
        self._last_storm = -1e18
        self._last_dump: Dict[str, float] = {}
        self.dumps: List[str] = []   # incident files written (diagnostics)
        self.events_recorded = 0

    # ------------------------------------------------------------------
    def record(self, kind: str, name: str, trace_id: str = "",
               attrs: Optional[Dict[str, Any]] = None) -> None:
        """Append one event to the ring. Never raises."""
        slot = (time.time(), time.monotonic_ns(), kind, name, trace_id,
                attrs)
        with self._mu:
            self._ring[self._seq % self.capacity] = slot
            self._seq += 1
            self.events_recorded += 1

    def note_rejection(self) -> bool:
        """Track an admission rejection; True when the storm threshold is
        crossed inside the window (caller then dumps). Reports at most one
        storm per window — a sustained storm keeps shedding far faster
        than anyone wants incident files (or dump threads). The timestamp
        list is pruned to the window, so memory stays bounded."""
        now = time.monotonic()
        with self._mu:
            self._reject_ts.append(now)
            cutoff = now - self.reject_storm_window_s
            self._reject_ts = [t for t in self._reject_ts if t >= cutoff]
            if len(self._reject_ts) < self.reject_storm_threshold or \
                    now - self._last_storm < self.reject_storm_window_s:
                return False
            self._last_storm = now
            return True

    def snapshot(self) -> List[tuple]:
        """Events oldest-first (the ring's current contents)."""
        with self._mu:
            n = min(self._seq, self.capacity)
            start = self._seq - n
            return [self._ring[i % self.capacity]
                    for i in range(start, self._seq)]

    # ------------------------------------------------------------------
    def dump(self, reason: str, trace_id: str = "",
             attrs: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the incident file: one `incident` header record followed
        by one `event` record per ring entry, every line valid under
        `spans.validate_record` (schema v2). Returns the path, or None
        when no dump directory is configured / the per-reason rate limit
        suppressed it. Never raises — the recorder must not worsen the
        failure it is documenting."""
        try:
            return self._dump(reason, trace_id, attrs)
        except Exception:
            return None

    def _dump(self, reason: str, trace_id: str,
              attrs: Optional[Dict[str, Any]]) -> Optional[str]:
        if not self.dump_dir:
            return None
        now = time.monotonic()
        with self._mu:
            last = self._last_dump.get(reason, -1e18)
            if now - last < _DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump[reason] = now
        events = self.snapshot()
        from ..utils import spans
        os.makedirs(self.dump_dir, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(
            self.dump_dir,
            f"incident-{ts}-{os.getpid()}-{_slug(reason)}.jsonl")
        lines = [spans.to_json_line(spans.incident_record(
            reason, trace_id=trace_id, n_events=len(events),
            attrs=attrs))]
        for i, ev in enumerate(events):
            ev_ts, t_ns, kind, name, ev_trace, ev_attrs = ev
            lines.append(spans.to_json_line({
                "v": spans.SCHEMA_VERSION, "type": "event",
                "seq": i, "ts": ev_ts, "t_ns": t_ns,
                "kind": kind, "name": name,
                "trace_id": ev_trace or "",
                "attrs": dict(ev_attrs or {}),
            }))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        with self._mu:
            self.dumps.append(path)
        return path


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in s)[:48]

