"""Live telemetry: process-wide metrics registry, health/scrape surface,
and the incident flight recorder (ARCHITECTURE.md "Live telemetry").

The query profiler (PR 4, utils/spans.py) answers "what did THAT query
do" after it finishes; this package answers "what is the ENGINE doing
right now" for a long-lived multi-tenant `TpuDeviceService`: scrapeable
counters/gauges/histograms fed from the existing seams, `/metrics` +
`/healthz` over HTTP and the service protocol, and a black-box ring that
dumps evidence when a query dies instead of finishing.

Layout:
  * `registry.py`  — counters/gauges/bounded-label histograms, Prometheus
    text render + parse-back.
  * `exporter.py`  — health snapshot + opt-in stdlib HTTP thread.
  * `recorder.py`  — flight-recorder ring + schema-validated incident
    dumps.
  * this module    — the facade the engine seams call. Off-path contract
    (mirrors faults._ACTIVE / sched.context._ACTIVE): with
    `spark.rapids.tpu.telemetry.enabled=false` (default) every hook below
    is one module-global bool check, no registry/recorder/HTTP objects
    exist, and zero threads are spawned — scripts/telemetry_matrix.sh
    gates it.

`configure(conf)` only ever ENABLES (idempotent); `shutdown()` tears
down explicitly (tests) — a second session with telemetry off must not
yank the surface out from under the session that turned it on.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional

from .exporter import TelemetryHttpServer, health_snapshot
from .recorder import FlightRecorder
from .registry import (DEFAULT_BUCKETS, OVERFLOW_LABEL, MetricsRegistry,
                       parse_prometheus)

__all__ = ["configure", "shutdown", "is_enabled", "registry",
           "flight_recorder", "http_server", "render_prometheus",
           "health_snapshot", "inc", "set_gauge", "observe", "flight",
           "count_rejection", "incident", "ops_baseline", "ops_finish",
           "register_prefetch", "MetricsRegistry", "FlightRecorder",
           "TelemetryHttpServer", "parse_prometheus", "OVERFLOW_LABEL",
       ]

_ACTIVE = False
_mu = threading.Lock()
_registry: Optional[MetricsRegistry] = None
_recorder: Optional[FlightRecorder] = None
_http: Optional[TelemetryHttpServer] = None
_conf = None

# live PrefetchIterators (exec/base.py registers when telemetry is on) for
# the queue-occupancy gauge; weak so a leaked iterator cannot pin batches
_prefetch_iters: "weakref.WeakSet" = weakref.WeakSet()

# q-error factors (1 = perfect estimate) and per-partition byte sizes —
# the two histogram families live telemetry's seconds-scale DEFAULT_BUCKETS
# cannot serve
QERROR_BUCKETS = (1.0, 1.5, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                  512.0, 2048.0)
BYTE_BUCKETS = (1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10, 1 << 20,
                4 << 20, 16 << 20, 64 << 20, 256 << 20, 1 << 30)


def is_enabled() -> bool:
    return _ACTIVE


def registry() -> Optional[MetricsRegistry]:
    return _registry


def flight_recorder() -> Optional[FlightRecorder]:
    return _recorder


def http_server() -> Optional[TelemetryHttpServer]:
    return _http


def render_prometheus() -> str:
    reg = _registry
    return reg.render() if reg is not None else ""


# --------------------------------------------------------------- lifecycle
def configure(conf) -> None:
    """Enable telemetry per `spark.rapids.tpu.telemetry.*` (no-op when the
    switch is off or telemetry is already up). Called from
    TpuSession.initialize_device."""
    global _ACTIVE, _registry, _recorder, _http, _conf
    if not conf.get("spark.rapids.tpu.telemetry.enabled"):
        return
    with _mu:
        if _ACTIVE:
            _conf = conf
            return
        reg = MetricsRegistry(max_series_per_family=conf.get(
            "spark.rapids.tpu.telemetry.labels.maxCardinality"))
        _install_families(reg)
        dump_dir = conf.get(
            "spark.rapids.tpu.telemetry.flightRecorder.dir") or conf.get(
            "spark.rapids.tpu.metrics.eventLog.dir") or ""
        rec = FlightRecorder(
            capacity=conf.get(
                "spark.rapids.tpu.telemetry.flightRecorder.capacity"),
            dump_dir=dump_dir,
            reject_storm_threshold=conf.get(
                "spark.rapids.tpu.telemetry.flightRecorder."
                "rejectStormThreshold"),
            reject_storm_window_s=conf.get(
                "spark.rapids.tpu.telemetry.flightRecorder."
                "rejectStormWindowSec"))
        _registry, _recorder, _conf = reg, rec, conf
        _ACTIVE = True
        from ..utils import spans as _spans
        _spans.set_flight_hook(_span_flight_hook)
        port = conf.get("spark.rapids.tpu.telemetry.http.port")
        if port is not None and port >= 0:
            try:
                _http = TelemetryHttpServer(
                    reg, conf,
                    host=conf.get("spark.rapids.tpu.telemetry.http.host"),
                    port=port).start()
            except OSError:
                _http = None  # a taken port must not fail device init


def shutdown() -> None:
    """Tear the telemetry surface down (tests / process exit)."""
    global _ACTIVE, _registry, _recorder, _http, _conf
    with _mu:
        _ACTIVE = False
        from ..utils import spans as _spans
        _spans.set_flight_hook(None)
        if _http is not None:
            _http.stop()
        _registry = _recorder = _http = _conf = None
        _prefetch_iters.clear()


def _span_flight_hook(sp, prof) -> None:
    """Every finished profiler span also lands in the incident ring (the
    'recent span/metric events' half of the flight recorder)."""
    rec = _recorder
    if rec is not None:
        rec.record(sp.kind, sp.name,
                   trace_id=getattr(prof, "trace_id", "") or "",
                   attrs=dict(sp.attrs) if sp.attrs else None)


# ----------------------------------------------------------- hot-path hooks
def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    if not _ACTIVE:
        return
    reg = _registry
    if reg is not None:
        reg.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: Any) -> None:
    if not _ACTIVE:
        return
    reg = _registry
    if reg is not None:
        reg.set(name, value, **labels)


def observe(name: str, value: float, **labels: Any) -> None:
    if not _ACTIVE:
        return
    reg = _registry
    if reg is not None:
        reg.observe(name, value, **labels)


def flight(kind: str, name: str, trace_id: Optional[str] = None,
           **attrs: Any) -> None:
    """Record one flight-recorder event, stamped with `trace_id` or the
    current trace (spans.current_trace) when one is active."""
    if not _ACTIVE:
        return
    rec = _recorder
    if rec is not None:
        if trace_id is None:
            from ..utils import spans
            trace_id = spans.current_trace() or ""
        rec.record(kind, name, trace_id=trace_id, attrs=attrs or None)


def count_rejection(tenant: str = "default") -> None:
    """One admission rejection: counter + flight event + storm detection
    (threshold crossings dump an incident — overload evidence survives
    even though every shed query dies without a profile). Callers hold
    the admission queue's condition variable, so everything here is
    lock-light; the storm DUMP (file IO) runs on a one-shot thread."""
    if not _ACTIVE:
        return
    reg, rec = _registry, _recorder
    if reg is not None:
        reg.inc("tpu_sched_rejected_total", 1, tenant=tenant)
    if rec is not None:
        from ..utils import spans
        rec.record("sched", "reject", trace_id=spans.current_trace() or "",
                   attrs={"tenant": tenant})
        if rec.note_rejection():
            threading.Thread(
                target=incident, args=("reject_storm",),
                kwargs={"tenant": tenant}, daemon=True,
                name="tpu-telemetry-incident").start()


def incident(reason: str, **attrs: Any) -> Optional[str]:
    """Terminal-failure hook: record the event, bump the incident counter,
    and dump the flight recorder. Returns the dump path (None when
    disabled/rate-limited)."""
    if not _ACTIVE:
        return None
    from ..utils import spans
    trace = spans.current_trace() or ""
    reg, rec = _registry, _recorder
    if reg is not None:
        reg.inc("tpu_incidents_total", 1, reason=reason)
    if rec is None:
        return None
    rec.record("incident", reason, trace_id=trace, attrs=attrs or None)
    return rec.dump(reason, trace_id=trace, attrs=attrs)


def register_prefetch(it) -> None:
    """Track a live PrefetchIterator for the queue-occupancy gauge."""
    if _ACTIVE:
        _prefetch_iters.add(it)


# -------------------------------------------- per-op throughput (MetricsSet)
def ops_baseline(root) -> Optional[List[tuple]]:
    """Snapshot every operator's MetricsSet before execution so
    `ops_finish` can feed THIS query's deltas (reused exec instances carry
    prior queries' values) into the per-op throughput counters."""
    if not _ACTIVE:
        return None
    out: List[tuple] = []

    def walk(node):
        ms = getattr(node, "metrics", None)
        if ms is not None and hasattr(ms, "snapshot"):
            out.append((getattr(node, "name", type(node).__name__), ms,
                        ms.snapshot()))
        for child in getattr(node, "children", ()):
            walk(child)

    try:
        walk(root)
    except Exception:
        return None
    return out


def ops_finish(baselines: Optional[List[tuple]]) -> None:
    if not _ACTIVE or not baselines:
        return
    reg = _registry
    if reg is None:
        return
    for name, ms, base in baselines:
        try:
            final = ms.snapshot()
        except Exception:
            continue
        rows = final.get("numOutputRows", 0) - base.get("numOutputRows", 0)
        batches = final.get("numOutputBatches", 0) - \
            base.get("numOutputBatches", 0)
        if rows > 0:
            reg.inc("tpu_op_output_rows_total", rows, op=name)
        if batches > 0:
            reg.inc("tpu_op_output_batches_total", batches, op=name)


# ------------------------------------------------------------ family setup
def _install_families(reg: MetricsRegistry) -> None:
    """Register every metric family once, with gauges sampling the engine
    singletons at scrape time (guarded reads: a singleton that does not
    exist yet samples as absent, never constructs)."""
    # queries / fallback
    reg.counter("tpu_queries_total",
                "Queries finished, by terminal status.", ["status"])
    reg.counter("tpu_cpu_fallback_reruns_total",
                "Silent CpuFallbackRequired whole-stage re-runs on the "
                "host engine.")
    reg.counter("tpu_op_output_rows_total",
                "Rows produced per operator family (MetricsSet deltas, "
                "fed at query end).", ["op"])
    reg.counter("tpu_op_output_batches_total",
                "Batches produced per operator family.", ["op"])
    reg.counter("tpu_incidents_total",
                "Flight-recorder incident dumps triggered, by reason.",
                ["reason"])

    # scheduler / admission
    reg.counter("tpu_sched_admissions_total",
                "Admission grants through any device door.", ["tenant"])
    reg.counter("tpu_sched_rejected_total",
                "Load-shed admission rejections (QueryRejectedError).",
                ["tenant"])
    reg.counter("tpu_sched_cancelled_total",
                "Queries cancelled while queued for admission.", ["tenant"])
    reg.counter("tpu_sched_deadline_total",
                "Deadline expiries while queued for admission.", ["tenant"])
    reg.histogram("tpu_sched_admission_wait_seconds",
                  "Wall time parked in the admission queue before grant "
                  "or typed unwind.", ["tenant"], buckets=DEFAULT_BUCKETS)
    reg.gauge("tpu_sched_queue_depth",
              "Waiters currently queued across live admission queues.",
              callback=_sched_gauge("depth"))
    reg.gauge("tpu_sched_holders",
              "Admission tokens currently held across live queues.",
              callback=_sched_gauge("holders"))
    reg.gauge("tpu_sched_peak_depth",
              "Deepest admission queue ever observed.",
              callback=_sched_gauge("peak"))
    reg.gauge("tpu_sched_shed_total",
              "Lifetime load-shed count across live admission queues.",
              callback=_sched_gauge("shed"))

    # memory budget + tenant quotas
    reg.gauge("tpu_memory_budget_bytes",
              "Device memory budget accounting: total/used/peak bytes.",
              ["kind"], callback=_budget_gauge)
    reg.gauge("tpu_memory_tenant_used_bytes",
              "Per-tenant device sub-quota ledger usage.", ["tenant"],
              callback=lambda: _tenant_gauge("tenant_used"))
    reg.gauge("tpu_memory_tenant_quota_bytes",
              "Per-tenant device sub-quota limits.", ["tenant"],
              callback=lambda: _tenant_gauge("tenant_quotas"))

    # spill catalog
    reg.gauge("tpu_catalog_bytes",
              "Live spillable-buffer bytes by storage tier.", ["tier"],
              callback=_catalog_tier_gauge)
    reg.gauge("tpu_catalog_handles",
              "Live spillable-buffer handles.", callback=_catalog_gauge(
                  lambda c: c.live_count))
    reg.gauge("tpu_catalog_host_used_bytes",
              "Host spill-store bytes in use.", callback=_catalog_gauge(
                  lambda c: c.host_used))
    reg.counter("tpu_spill_bytes_total",
                "Bytes spilled, by destination tier.", ["tier"])

    # compile service
    reg.gauge("tpu_compile_stats",
              "Compile-service lifetime accounting "
              "(hits/misses/compiles/...).", ["event"],
              callback=_compile_stats_gauge)
    reg.gauge("tpu_compile_cache_programs",
              "Programs resident in the in-memory compile cache.",
              callback=_compile_cache_gauge)

    # shuffle data plane
    reg.counter("tpu_shuffle_fetch_bytes_total",
                "Shuffle frame bytes read (local + remote fetch).")
    reg.counter("tpu_shuffle_write_bytes_total",
                "Serialized shuffle bytes written to the block store.")
    reg.counter("tpu_shuffle_fetch_retries_total",
                "Shuffle fetch retry attempts.")
    reg.counter("tpu_shuffle_fetch_refetches_total",
                "Corrupt-frame refetches.")
    reg.counter("tpu_shuffle_fetch_failovers_total",
                "Fetches recovered via failover peers.")

    # pipeline
    reg.counter("tpu_prefetch_batches_total",
                "Batches moved through pipeline prefetch queues.")
    reg.gauge("tpu_prefetch_queue_occupancy",
              "Batches currently parked across live prefetch queues.",
              callback=_prefetch_gauge)

    # durable persistence (utils/durable.py + rescache/persist.py): tier
    # degradations and persistent result-tier traffic. A nonzero degraded
    # counter means a worker lost its warm-restart story for that tier —
    # the alert the chaos gate's disk-full campaign asserts fires.
    reg.counter("tpu_persist_degraded_total",
                "Durable tiers (compile cache / stats history / event log "
                "/ persistent result tier) degraded to memory-only after "
                "an IO failure.", ["tier"])
    reg.counter("tpu_rescache_persist_total",
                "Persistent result-tier operations (store / hit / warmed "
                "/ poisoned).", ["event"])

    # fleet supervisor (fleet/supervisor.py): respawns of crashed workers
    reg.counter("tpu_fleet_worker_restarts_total",
                "Worker processes respawned by the fleet supervisor.",
                ["worker"])

    # result & fragment cache (rescache/)
    reg.counter("tpu_rescache_hits_total",
                "Result/fragment-cache hits, by seam and tenant.",
                ["seam", "tenant"])
    reg.counter("tpu_rescache_misses_total",
                "Result/fragment-cache misses, by seam and tenant.",
                ["seam", "tenant"])
    reg.counter("tpu_rescache_evictions_total",
                "Cache entries evicted, by reason (capacity/invalidate).",
                ["reason"])
    reg.counter("tpu_rescache_singleflight_waits_total",
                "Queries that parked behind another query computing the "
                "same fingerprint.", ["tenant"])
    reg.counter("tpu_rescache_degraded_total",
                "Cache operations degraded to recompute (cache.fragment "
                "faults, mid-flight evictions).")
    reg.gauge("tpu_rescache_bytes",
              "Bytes held by the result/fragment cache, by entry kind "
              "(frags ride the spill catalog tiers; table/blob are host).",
              ["kind"], callback=_rescache_bytes_gauge)
    reg.gauge("tpu_rescache_entries",
              "Live result/fragment-cache entries.",
              callback=_rescache_gauge(lambda c: c.entry_count))

    # explicit df.cache() relations (datasources/cache.py): blob bytes
    # held by live CachedRelations — released on unpersist()
    reg.gauge("tpu_cached_relation_bytes",
              "Parquet-blob bytes held by materialized df.cache() "
              "relations (drops to 0 on unpersist).",
              callback=_cached_relation_gauge)

    # dynamic file pruning (io/dynamic_pruning.py): footer-read errors
    # keep the file (never a correctness gate) but degrade pruning — a
    # rising counter means the optimization is silently disengaging
    reg.counter("tpu_dpp_footer_errors_total",
                "Parquet footer/statistics read errors during dynamic "
                "pruning (file/row group kept unpruned).")

    # runtime statistics (stats/): history traffic, estimate quality,
    # skew evidence. The q-error histogram buckets are error FACTORS
    # (1 = perfect), the partition-bytes histogram buckets are BYTES —
    # a wide spread there is the skew signal aggregate shuffle byte
    # counters cannot show
    reg.counter("tpu_stats_history_hits_total",
                "Cardinality-history lookups answered, by lookup kind "
                "(rows / selectivity / stage / skew).", ["kind"])
    reg.counter("tpu_stats_history_misses_total",
                "Cardinality-history lookups missed, by lookup kind.",
                ["kind"])
    reg.counter("tpu_stats_records_total",
                "Operator actuals recorded into the statistics history.")
    reg.counter("tpu_stats_skew_detections_total",
                "Exchanges whose observed per-partition bytes crossed "
                "the skew factor.")
    reg.histogram("tpu_stats_qerror",
                  "Per-operator q-error distribution (max(est/actual, "
                  "actual/est); 1 = perfect estimate).", ["op"],
                  buckets=QERROR_BUCKETS)
    reg.gauge("tpu_stats_history_entries",
              "Entries resident in the statistics history LRU.",
              callback=_stats_history_gauge)
    reg.histogram("tpu_exchange_partition_bytes",
                  "Serialized bytes per exchange output partition, fed "
                  "at shuffle-write close (spread across buckets = "
                  "partition skew).", buckets=BYTE_BUCKETS)

    # live query introspection (live/): the in-flight view. Cardinality
    # is bounded by concurrent queries (itself bounded by admission), so
    # per-query_id progress labels stay far under the registry cap; the
    # callbacks read the live registry singleton without constructing it
    reg.gauge("tpu_live_queries",
              "In-flight queries tracked by the live registry, by "
              "tenant.", ["tenant"], callback=_live_queries_gauge)
    reg.gauge("tpu_live_query_progress",
              "Progress fraction (0..1) per in-flight query with "
              "statistics-history expectations; rows-only queries are "
              "omitted.", ["query_id"], callback=_live_progress_gauge)

    # sharded mesh execution (mesh/ + exec/exchange.py ICI path): the
    # collective data plane's traffic, host-plane degrades, and the
    # per-chip HBM ledgers (callback reads the budget singleton — the
    # gauge never imports the mesh package)
    reg.counter("tpu_mesh_exchanges_total",
                "Mesh all-to-all collectives executed (the ICI shuffle "
                "data plane).")
    reg.counter("tpu_mesh_ici_bytes_total",
                "Bytes moved over the ICI collective (post-exchange slot "
                "plane) instead of the host shuffle.")
    reg.counter("tpu_mesh_degraded_total",
                "Mesh-active exchanges that degraded to the host data "
                "plane on a shard-count vs partition-count mismatch.")
    reg.gauge("tpu_mesh_chip_hbm_bytes",
              "Chip-tagged device-resident bytes per mesh chip "
              "(spark.rapids.tpu.mesh.hbmPerChip sub-budgets).", ["chip"],
              callback=_mesh_chip_gauge)

    # fleet gateway (fleet/): route decisions + per-worker pool gauges.
    # Callbacks observe live WorkerRegistries through sys.modules ONLY —
    # a process that never started a gateway never imports the package
    # (the fleet-off zero-state contract).
    reg.counter("tpu_fleet_route_total",
                "Gateway routing decisions (affinity / load / failover / "
                "shed / pinned).", ["decision"])
    reg.counter("tpu_fleet_failover_total",
                "run_plan dispatches failed over AWAY from a worker "
                "(connection loss / breaker trip mid-flight).", ["worker"])
    reg.gauge("tpu_fleet_breaker_state",
              "Per-worker circuit breaker (0=closed, 1=half-open, "
              "2=open).", ["worker"], callback=_fleet_gauge("breaker"))
    reg.gauge("tpu_fleet_outstanding",
              "Queries currently dispatched per worker (the gateway's "
              "power-of-two load signal).", ["worker"],
              callback=_fleet_gauge("outstanding"))
    reg.gauge("tpu_fleet_draining",
              "1 while a worker is admin-drained (in-flight finishes, "
              "nothing new routes).", ["worker"],
              callback=_fleet_gauge("draining"))


# gauge callbacks: read singletons WITHOUT constructing them ----------------
def _mesh_chip_gauge():
    from ..memory.budget import MemoryBudget
    b = MemoryBudget._instance
    if b is None or not getattr(b, "chip_budgets", None):
        return {}
    with b._lock:
        return {(str(c),): v for c, v in b.chip_used.items()}


def _budget_gauge():
    from ..memory.budget import MemoryBudget
    b = MemoryBudget._instance
    if b is None:
        return {}
    return {("total",): b.total, ("used",): b.used, ("peak",): b.peak_used}


def _tenant_gauge(field: str):
    from ..memory.budget import MemoryBudget
    b = MemoryBudget._instance
    if b is None:
        return {}
    with b._lock:  # concurrent reserve/release mutate the ledgers
        return {(t,): v for t, v in getattr(b, field).items()}


def _catalog_gauge(fn):
    def cb():
        from ..memory.catalog import BufferCatalog
        c = BufferCatalog._instance
        return fn(c) if c is not None else None
    return cb


def _catalog_tier_gauge():
    from ..memory.catalog import BufferCatalog
    c = BufferCatalog._instance
    if c is None:
        return {}
    with c._lock:  # register/remove mutate the dict concurrently
        entries = list(c._entries.values())
    per_tier: Dict[tuple, int] = {}
    for e in entries:
        key = (e.tier.name,)
        per_tier[key] = per_tier.get(key, 0) + e.nbytes
    return per_tier


def _compile_stats_gauge():
    from ..compile.service import CompileService
    svc = CompileService._instance
    if svc is None:
        return {}
    return {(k,): v for k, v in svc.stats.totals().items()}


def _compile_cache_gauge():
    from ..compile.service import CompileService
    svc = CompileService._instance
    return svc.cached_programs() if svc is not None else None


def _sched_gauge(which: str):
    # time-bounded cv acquire: a wedged admission queue (the failure
    # healthz exists to catch) must skew one sample, never hang every
    # scrape thread forever on an untimed lock
    def cb():
        from ..sched.scheduler import live_admission_queues
        total = 0
        for q in live_admission_queues():
            if which == "peak":
                total = max(total, q.peak_depth)
            elif which == "shed":
                total += q.shed_count
            elif q.cv.acquire(timeout=0.5):
                try:
                    if which == "depth":
                        total += q._depth_locked()
                    else:  # holders
                        total += q.holders
                finally:
                    q.cv.release()
        return total
    return cb


def _prefetch_gauge():
    total = 0
    for it in list(_prefetch_iters):
        q = getattr(it, "_q", None)
        if q is not None:
            total += q.qsize()
    return total


def _rescache_gauge(fn):
    def cb():
        from .. import rescache
        c = rescache.get()
        return fn(c) if c is not None else None
    return cb


def _rescache_bytes_gauge():
    from .. import rescache
    c = rescache.get()
    if c is None:
        return {}
    return {(kind,): v for kind, v in c.bytes_by_kind().items()}


def _stats_history_gauge():
    from .. import stats
    h = stats.get()
    return h.entry_count if h is not None else None


def _live_queries_gauge():
    from .. import live
    reg = live.get()
    if reg is None:
        return {}
    out: Dict[tuple, float] = {}
    for e in reg.inflight():
        key = (e.tenant,)
        out[key] = out.get(key, 0) + 1
    return out


def _live_progress_gauge():
    from .. import live
    reg = live.get()
    if reg is None:
        return {}
    out: Dict[tuple, float] = {}
    for e in reg.inflight():
        p = e.progress()
        if p is not None:
            out[(e.query_id,)] = p
    return out


def _fleet_gauge(which: str):
    def cb():
        import sys
        mod = sys.modules.get("spark_rapids_tpu.fleet.registry")
        if mod is None:
            return {}  # no gateway in this process — and never import one
        out: Dict[tuple, float] = {}
        for reg in mod.live_registries():
            for name, w in list(reg.workers.items()):
                if which == "breaker":
                    v = mod.BREAKER_GAUGE.get(w.breaker.state, 0)
                elif which == "outstanding":
                    v = w.outstanding
                else:  # draining
                    v = 1 if w.draining else 0
                out[(name,)] = out.get((name,), 0) + v
        return out
    return cb


def _cached_relation_gauge():
    from ..datasources import cache as _dscache
    total = 0
    for node in list(_dscache.live_cached_execs()):
        rel = node.relation
        if rel is not None:
            total += rel.size_bytes
    return total
