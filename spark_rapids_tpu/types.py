"""Spark-compatible data type system.

Mirrors the type surface the reference supports on GPU (see SURVEY.md §2.2 TypeChecks /
`sql-plugin/.../TypeChecks.scala:171` TypeSig): boolean, byte/short/int/long, float/double,
string, date, timestamp, decimal, null, plus nested array/struct/map (nested types are
represented but only partially executable on device in this round).

Physical mapping (TPU-first):
  BOOLEAN   -> bool_
  BYTE      -> int8        SHORT -> int16     INT -> int32     LONG -> int64
  FLOAT     -> float32     DOUBLE -> float64 (on TPU, f64 computes as f32 pairs; we keep
                           float32 device compute for DOUBLE only when explicitly allowed,
                           default is exact float64 via XLA's f64 emulation on host path)
  STRING    -> uint8[n, w] byte matrix + int32 lengths
  DATE      -> int32 days since epoch (Spark semantics)
  TIMESTAMP -> int64 microseconds since epoch (Spark semantics)
  DECIMAL(p<=18, s) -> int64 unscaled; DECIMAL(p>18) -> 2x int64 limbs (limited support)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "DataType", "NumericType", "IntegralType", "FractionalType",
    "BooleanType", "ByteType", "ShortType", "IntegerType", "LongType",
    "FloatType", "DoubleType", "StringType", "BinaryType", "DateType",
    "TimestampType", "DecimalType", "NullType", "ArrayType", "StructType",
    "StructField", "MapType", "BOOLEAN", "BYTE", "SHORT", "INT", "LONG",
    "FLOAT", "DOUBLE", "STRING", "BINARY", "DATE", "TIMESTAMP", "NULL",
]


@dataclasses.dataclass(frozen=True)
class DataType:
    """Base of the Spark-style type lattice."""

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    # --- physical properties -------------------------------------------------
    @property
    def np_dtype(self) -> Optional[np.dtype]:
        """numpy dtype of the primary device buffer, None for non-primitive."""
        return None

    @property
    def is_primitive(self) -> bool:
        return self.np_dtype is not None

    @property
    def is_nested(self) -> bool:
        return isinstance(self, (ArrayType, StructType, MapType))

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return self.simple_string()


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class BooleanType(DataType):
    @property
    def np_dtype(self):
        return np.dtype(np.bool_)


@dataclasses.dataclass(frozen=True, repr=False)
class ByteType(IntegralType):
    @property
    def np_dtype(self):
        return np.dtype(np.int8)


@dataclasses.dataclass(frozen=True, repr=False)
class ShortType(IntegralType):
    @property
    def np_dtype(self):
        return np.dtype(np.int16)


@dataclasses.dataclass(frozen=True, repr=False)
class IntegerType(IntegralType):
    @property
    def np_dtype(self):
        return np.dtype(np.int32)


@dataclasses.dataclass(frozen=True, repr=False)
class LongType(IntegralType):
    @property
    def np_dtype(self):
        return np.dtype(np.int64)


@dataclasses.dataclass(frozen=True, repr=False)
class FloatType(FractionalType):
    @property
    def np_dtype(self):
        return np.dtype(np.float32)


@dataclasses.dataclass(frozen=True, repr=False)
class DoubleType(FractionalType):
    @property
    def np_dtype(self):
        return np.dtype(np.float64)


@dataclasses.dataclass(frozen=True, repr=False)
class StringType(DataType):
    """Variable-length UTF-8. Device layout: uint8[n, width] + int32[n] lengths."""

    @property
    def np_dtype(self):
        return None

    def simple_string(self) -> str:
        return "string"


@dataclasses.dataclass(frozen=True, repr=False)
class BinaryType(DataType):
    def simple_string(self) -> str:
        return "binary"


@dataclasses.dataclass(frozen=True, repr=False)
class DateType(DataType):
    """Days since 1970-01-01 (proleptic Gregorian), stored int32."""

    @property
    def np_dtype(self):
        return np.dtype(np.int32)


@dataclasses.dataclass(frozen=True, repr=False)
class TimestampType(DataType):
    """Microseconds since epoch UTC, stored int64 (Spark TimestampType)."""

    @property
    def np_dtype(self):
        return np.dtype(np.int64)


@dataclasses.dataclass(frozen=True, repr=False)
class DecimalType(FractionalType):
    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_DIGITS = 18

    def __post_init__(self):
        if not (0 < self.precision <= self.MAX_PRECISION):
            raise ValueError(f"decimal precision out of range: {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"decimal scale out of range: {self.scale}")

    @property
    def np_dtype(self):
        # <=18 digits fits in an int64 unscaled value; wider uses limb pairs.
        if self.precision <= self.MAX_LONG_DIGITS:
            return np.dtype(np.int64)
        return None

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    @staticmethod
    def bounded(precision: int, scale: int) -> "DecimalType":
        return DecimalType(min(precision, DecimalType.MAX_PRECISION),
                           min(scale, DecimalType.MAX_PRECISION))


@dataclasses.dataclass(frozen=True, repr=False)
class NullType(DataType):
    def simple_string(self) -> str:
        return "void"


@dataclasses.dataclass(frozen=True, repr=False)
class ArrayType(DataType):
    element_type: DataType = dataclasses.field(default_factory=lambda: NullType())
    contains_null: bool = True

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True, repr=False)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"

    def field_names(self):
        return [f.name for f in self.fields]


@dataclasses.dataclass(frozen=True, repr=False)
class MapType(DataType):
    key_type: DataType = dataclasses.field(default_factory=lambda: NullType())
    value_type: DataType = dataclasses.field(default_factory=lambda: NullType())
    value_contains_null: bool = True

    def simple_string(self) -> str:
        return (f"map<{self.key_type.simple_string()},"
                f"{self.value_type.simple_string()}>")


# Singletons, Spark-style.
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

_INTEGRAL_ORDER = {ByteType: 0, ShortType: 1, IntegerType: 2, LongType: 3}

_SIMPLE_NAMES = {
    "boolean": BOOLEAN, "byte": BYTE, "short": SHORT, "integer": INT,
    "long": LONG, "float": FLOAT, "double": DOUBLE, "string": STRING,
    "binary": BINARY, "date": DATE, "timestamp": TIMESTAMP, "void": NULL,
}


def parse_type(s: str) -> DataType:
    """Inverse of simple_string() for flat types (wire metadata / test specs).
    Nested types are not wire-serialized (they are not device-backed yet)."""
    s = s.strip()
    if s in _SIMPLE_NAMES:
        return _SIMPLE_NAMES[s]
    if s.startswith("decimal(") and s.endswith(")"):
        p, sc = s[len("decimal("):-1].split(",")
        return DecimalType(int(p), int(sc))
    raise ValueError(f"cannot parse type string {s!r}")


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Binary-arithmetic result type, matching Spark's implicit widening for the
    non-decimal numeric lattice (byte<short<int<long<float<double)."""
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise ValueError("decimal promotion handled by the expression layer")
    if isinstance(a, DoubleType) or isinstance(b, DoubleType):
        return DOUBLE
    if isinstance(a, FloatType) or isinstance(b, FloatType):
        return FLOAT
    oa = _INTEGRAL_ORDER[type(a)]
    ob = _INTEGRAL_ORDER[type(b)]
    return (a, b)[ob > oa]


def from_arrow(at) -> DataType:
    """Map a pyarrow DataType to ours."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOLEAN
    if pa.types.is_int8(at):
        return BYTE
    if pa.types.is_int16(at):
        return SHORT
    if pa.types.is_int32(at):
        return INT
    if pa.types.is_int64(at):
        return LONG
    if pa.types.is_float32(at):
        return FLOAT
    if pa.types.is_float64(at):
        return DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BINARY
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return NULL
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(tuple(
            StructField(f.name, from_arrow(f.type), f.nullable) for f in at))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    raise TypeError(f"unsupported arrow type: {at}")


def to_arrow(dt: DataType):
    import pyarrow as pa
    m = {
        BooleanType: pa.bool_(), ByteType: pa.int8(), ShortType: pa.int16(),
        IntegerType: pa.int32(), LongType: pa.int64(), FloatType: pa.float32(),
        DoubleType: pa.float64(), StringType: pa.string(), BinaryType: pa.binary(),
        DateType: pa.date32(), TimestampType: pa.timestamp("us", tz="UTC"),
        NullType: pa.null(),
    }
    t = type(dt)
    if t in m:
        return m[t]
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, to_arrow(f.data_type), f.nullable)
                          for f in dt.fields])
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key_type), to_arrow(dt.value_type))
    raise TypeError(f"unsupported type: {dt}")
