"""DataFrame frontend — builds CPU physical plans (the Catalyst stand-in's user
API). Thin by design: the interesting machinery is the plan rewrite underneath,
exactly as in the reference where user code is ordinary Spark SQL."""

from __future__ import annotations

from typing import Optional, Sequence, Union

from .expr.aggregates import AggregateFunction
from .expr.base import AttributeReference, Expression, output_name
from .plan import nodes as N


def _as_expr(e: Union[str, Expression]) -> Expression:
    return AttributeReference(e) if isinstance(e, str) else e


class GroupedData:
    def __init__(self, df: "DataFrame", keys: Sequence[Expression]):
        self.df = df
        self.keys = [_as_expr(k) for k in keys]

    def agg(self, **named_aggs: AggregateFunction) -> "DataFrame":
        aggs = [N.AggExpr(f, name) for name, f in named_aggs.items()]
        return DataFrame(self.df.session,
                         N.CpuHashAggregateExec(self.keys, aggs, self.df.plan))

    def _key_names(self):
        names = []
        for k in self.keys:
            if not isinstance(k, AttributeReference):
                raise ValueError("pandas group operations require plain "
                                 "column keys")
            names.append(k.col_name)
        return names

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(group pd.DataFrame) -> pd.DataFrame with `schema` (Spark's
        GroupedData.applyInPandas; GpuFlatMapGroupsInPandasExec)."""
        from .udf.pandas_execs import CpuFlatMapGroupsInPandasExec
        return DataFrame(self.df.session, CpuFlatMapGroupsInPandasExec(
            self._key_names(), fn, _as_schema(schema), self.df.plan))

    def agg_in_pandas(self, **named) -> "DataFrame":
        """Grouped pandas-UDF aggregation: each kwarg is
        (fn, return_type, *arg_columns); fn(*pd.Series) -> scalar
        (Spark's series-to-scalar pandas_udf; GpuAggregateInPandasExec)."""
        from .udf.pandas_execs import CpuAggregateInPandasExec, PandasAgg
        aggs = [PandasAgg(name, spec[0], spec[1], list(spec[2:]))
                for name, spec in named.items()]
        return DataFrame(self.df.session, CpuAggregateInPandasExec(
            self._key_names(), aggs, self.df.plan))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)


class CoGroupedData:
    def __init__(self, left: GroupedData, right: GroupedData):
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(left_group_df, right_group_df) -> pd.DataFrame with `schema`
        (Spark's cogrouped applyInPandas; GpuFlatMapCoGroupsInPandasExec).
        Co-groups form over the union of both sides' key values."""
        from .udf.pandas_execs import CpuCoGroupsInPandasExec
        return DataFrame(self.left.df.session, CpuCoGroupsInPandasExec(
            self.left._key_names(), self.right._key_names(), fn,
            _as_schema(schema), self.left.df.plan, self.right.df.plan))


def _as_schema(schema):
    from .columnar.batch import Schema
    if isinstance(schema, Schema):
        return schema
    import pyarrow as pa
    if isinstance(schema, pa.Schema):
        return Schema.from_arrow(schema)
    # [(name, DataType), ...]
    return Schema(tuple(n for n, _ in schema),
                  tuple(t for _, t in schema))


class DataFrame:
    def __init__(self, session, plan: N.PhysicalPlan):
        self.session = session
        self.plan = plan

    @property
    def schema(self):
        return self.plan.output

    def __getitem__(self, name: str) -> Expression:
        i = self.plan.output.index_of(name)
        return AttributeReference(name, self.plan.output.types[i])

    def select(self, *exprs: Union[str, Expression],
               **named: Expression) -> "DataFrame":
        from .expr.base import Alias
        projs = [_as_expr(e) for e in exprs]
        projs.extend(Alias(_as_expr(e), nm) for nm, e in named.items())
        return DataFrame(self.session, N.CpuProjectExec(projs, self.plan))

    def filter(self, condition: Expression) -> "DataFrame":
        return DataFrame(self.session, N.CpuFilterExec(condition, self.plan))

    where = filter

    def group_by(self, *keys: Union[str, Expression]) -> GroupedData:
        return GroupedData(self, [_as_expr(k) for k in keys])

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(iterator of pd.DataFrame) -> iterator of pd.DataFrame with
        `schema` (Spark's DataFrame.mapInPandas; GpuMapInPandasExec).
        Input re-chunks to spark.rapids.sql.batchSizeRows."""
        from .udf.pandas_execs import CpuMapInPandasExec
        return DataFrame(self.session, CpuMapInPandasExec(
            fn, _as_schema(schema), self.plan,
            conf=getattr(self.session, "conf", None)))

    def window_in_pandas(self, partition_by=(), **named) -> "DataFrame":
        """Each kwarg is (fn, return_type, *arg_columns); fn(*pd.Series)
        -> scalar broadcast over its partition (Spark's windowInPandas
        with an unbounded frame; GpuWindowInPandasExecBase)."""
        from .udf.pandas_execs import CpuWindowInPandasExec, PandasAgg
        keys = [partition_by] if isinstance(partition_by, str) \
            else list(partition_by)
        aggs = [PandasAgg(name, spec[0], spec[1], list(spec[2:]))
                for name, spec in named.items()]
        return DataFrame(self.session, CpuWindowInPandasExec(
            keys, aggs, self.plan))

    def agg(self, **named_aggs: AggregateFunction) -> "DataFrame":
        return GroupedData(self, []).agg(**named_aggs)

    def join(self, other: "DataFrame", on: Union[str, Sequence[str], None] = None,
             how: str = "inner", condition=None) -> "DataFrame":
        """Equi join on `on` keys (optionally with an extra `condition`
        predicate over the combined row), or — with no keys — a cartesian /
        pure-condition nested loop join. `how` additionally accepts "cross"
        and "existence" (left rows + bool `exists` column)."""
        keys = [] if on is None else \
            ([on] if isinstance(on, str) else list(on))
        lk = [_as_expr(k) for k in keys]
        rk = [_as_expr(k) for k in keys]
        return DataFrame(self.session,
                         N.CpuHashJoinExec(self.plan, other.plan, lk, rk, how,
                                           condition=condition))

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, how="cross")

    def stack(self, n: int, *exprs, prefix: str = "col") -> "DataFrame":
        """stack(n, e1, ..., em): n output rows per input row with
        ceil(m/n) generated columns (Spark's Stack generator,
        `GpuOverrides.scala` Stack). Lowered onto the existing generate
        machinery: explode of an n-slot array of structs, then a
        flattening projection — no dedicated exec needed."""
        import math
        from .expr.base import Alias
        from .expr.collections import (CreateArray, CreateNamedStruct,
                                       Explode, GetStructField, NullLike)
        m = len(exprs)

        def resolved(e):
            e = _as_expr(e)
            if isinstance(e, AttributeReference):
                try:
                    e.data_type
                except ValueError:  # untyped col(...): the schema knows
                    i = self.plan.output.index_of(e.col_name)
                    return AttributeReference(e.col_name,
                                              self.plan.output.types[i])
            return e

        es = [resolved(e) for e in exprs]
        ncols = max(math.ceil(m / max(n, 1)), 1)
        names = [f"{prefix}{c}" for c in range(ncols)]
        rows = []
        for r in range(n):
            fields = []
            for c in range(ncols):
                i = r * ncols + c
                fields.append(es[i] if i < m else NullLike(es[c]))
            rows.append(CreateNamedStruct(names, fields))
        gen = Explode(CreateArray(rows))
        exploded = DataFrame(self.session,
                             N.CpuGenerateExec(gen, self.plan))
        # the generated struct is the LAST column: bind by ordinal so a
        # pre-existing column literally named "col" cannot shadow it
        from .expr.base import BoundReference
        struct_ref = BoundReference(len(self.plan.output.names),
                                    rows[0].data_type)
        keep = [nm for nm in self.plan.output.names]
        flat = [Alias(GetStructField(struct_ref, c), names[c])
                for c in range(ncols)]
        return exploded.select(*keep, *[f for f in flat])

    def explode(self, column, outer: bool = False,
                position: bool = False) -> "DataFrame":
        """Append explode(column) rows: one output row per array element
        (child columns retained, exploded element as `col`, plus `pos` when
        position=True; outer=True keeps null/empty arrays as one null row)."""
        from .expr.collections import Explode
        gen = Explode(_as_expr(column), position=position, outer=outer)
        return DataFrame(self.session, N.CpuGenerateExec(gen, self.plan))

    def sort(self, *orders, ascending: bool = True,
             nulls_first: Optional[bool] = None) -> "DataFrame":
        specs = []
        for o in orders:
            if isinstance(o, tuple):
                e, asc, nf = o
                specs.append((_as_expr(e), asc, nf))
            else:
                nf = nulls_first if nulls_first is not None else ascending
                specs.append((_as_expr(o), ascending, nf))
        return DataFrame(self.session, N.CpuSortExec(specs, self.plan))

    order_by = sort

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        """Deterministic Bernoulli sample without replacement
        (GpuSampleExec analog; both engines pick identical rows per seed)."""
        return DataFrame(self.session,
                         N.CpuSampleExec(fraction, seed, self.plan))

    def limit(self, n: int, offset: int = 0) -> "DataFrame":
        return DataFrame(self.session, N.CpuLimitExec(n, self.plan, offset))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session, N.CpuUnionExec([self.plan, other.plan]))

    def window(self, partition_by=(), order_by=(),
               **named_fns) -> "DataFrame":
        """Append window-function columns. `partition_by`: columns/exprs;
        `order_by`: columns or (expr, ascending, nulls_first) tuples; named
        values are WindowFunction instances or AggregateFunctions (wrapped in
        the Spark-default frame). Output rows come back sorted by
        (partition, order)."""
        from .expr.windowexprs import WindowAggregate, WindowFunction
        part = [_as_expr(p) for p in partition_by]
        orders = []
        for o in order_by:
            if isinstance(o, tuple):
                e, asc, nf = o
                orders.append((_as_expr(e), asc, nf))
            else:
                orders.append((_as_expr(o), True, True))
        fns = []
        for name, f in named_fns.items():
            if isinstance(f, AggregateFunction):
                f = WindowAggregate(f)
            if not isinstance(f, WindowFunction):
                raise TypeError(f"{name}: expected a window/aggregate "
                                f"function, got {type(f).__name__}")
            fns.append((f, name))
        return DataFrame(self.session,
                         N.CpuWindowExec(fns, part, orders, self.plan))

    def repartition(self, num_partitions: int,
                    *keys: Union[str, Expression]) -> "DataFrame":
        """Partitioned exchange: hash by keys, or round-robin with no keys
        (matching Spark's df.repartition). Non-column key expressions are
        projected into temp columns around the exchange, like Spark's planner
        does before hash partitioning."""
        if num_partitions < 1:
            raise ValueError(
                f"num_partitions must be positive, got {num_partitions}")
        if not keys:
            spec = N.RoundRobinPartitionSpec(num_partitions)
            return DataFrame(self.session,
                             N.CpuShuffleExchangeExec(spec, self.plan))
        key_exprs = [_as_expr(k) for k in keys]
        if all(isinstance(k, AttributeReference) for k in key_exprs):
            spec = N.HashPartitionSpec(key_exprs, num_partitions)
            return DataFrame(self.session,
                             N.CpuShuffleExchangeExec(spec, self.plan))
        from .expr.base import Alias
        orig = [AttributeReference(n) for n in self.schema.names]
        tmp_names, proj = [], list(orig)
        for i, k in enumerate(key_exprs):
            if isinstance(k, AttributeReference):
                tmp_names.append(k.col_name)
            else:
                name = f"__part_key_{i}"
                tmp_names.append(name)
                proj.append(Alias(k, name))
        pre = N.CpuProjectExec(proj, self.plan)
        spec = N.HashPartitionSpec([AttributeReference(n) for n in tmp_names],
                                   num_partitions)
        exch = N.CpuShuffleExchangeExec(spec, pre)
        post = N.CpuProjectExec(orig, exch)
        return DataFrame(self.session, post)

    def repartition_by_range(self, num_partitions: int,
                             key: Union[str, Expression],
                             ascending: bool = True) -> "DataFrame":
        if num_partitions < 1:
            raise ValueError(
                f"num_partitions must be positive, got {num_partitions}")
        spec = N.RangePartitionSpec(_as_expr(key), num_partitions, ascending,
                                    nulls_first=ascending)
        return DataFrame(self.session, N.CpuShuffleExchangeExec(spec,
                                                                self.plan))

    def coalesce_partitions(self) -> "DataFrame":
        return DataFrame(self.session, N.CpuShuffleExchangeExec(None,
                                                                self.plan))

    def collect(self):
        """Execute and return a pyarrow Table."""
        return self.session.execute_plan(self.plan)

    def write_parquet(self, path: str, partition_by=None, mode: str = "error",
                      **options):
        from .io.writer import write_table
        conf = self.session.conf
        codec = (options.get("compression") or "snappy").upper()
        if (not partition_by and
                codec in ("SNAPPY", "ZSTD", "UNCOMPRESSED", "NONE") and
                conf.get(
                    "spark.rapids.sql.format.parquet.deviceWrite.enabled")):
            from .errors import PlanNotFullyOnDevice
            from .io.parquet_device_write import schema_supported
            from .io.writer import write_device_parquet
            if schema_supported(self.schema):
                try:
                    batches = self.session.execute_plan_device_batches(
                        self.plan)
                except PlanNotFullyOnDevice:
                    pass  # CPU sections in the plan: host write below
                else:
                    return write_device_parquet(
                        batches, self.schema, path, mode,
                        codec="UNCOMPRESSED" if codec == "NONE" else codec)
        return write_table(self.collect(), path, "parquet", partition_by,
                           mode, **options)

    def write_orc(self, path: str, partition_by=None, mode: str = "error",
                  **options):
        return self._write_text_format("orc", path, partition_by, mode,
                                       **options)

    def write_csv(self, path: str, partition_by=None, mode: str = "error",
                  **options):
        return self._write_text_format("csv", path, partition_by, mode,
                                       **options)

    def _write_text_format(self, fmt, path, partition_by, mode, **options):
        """Device-encoded ORC/CSV write with a single plan execution:
        when the device encoder declines (quoting, unsupported types) the
        already-materialized device batches convert to Arrow for the host
        writer — the plan never runs twice."""
        from .errors import PlanNotFullyOnDevice
        from .io.parquet_device import DeviceDecodeUnsupported
        from .io.writer import write_blob, write_table
        batches = None
        if not partition_by and self.session.conf.get(
                f"spark.rapids.sql.format.{fmt}.deviceWrite.enabled"):
            if fmt == "orc":
                from .io.orc_device_write import (
                    device_encode_orc as encode,
                    orc_write_schema_supported as supported)
            else:
                from .io.csv_device_write import (
                    csv_write_schema_supported as supported,
                    device_encode_csv as encode)
            if supported(self.schema):
                try:
                    batches = self.session.execute_plan_device_batches(
                        self.plan)
                    blob = encode(batches, self.schema)
                    rows = sum(int(b.row_count()) for b in batches)
                    return write_blob(path, mode, blob, fmt, rows)
                except PlanNotFullyOnDevice:
                    batches = None  # CPU sections: host path executes
                except DeviceDecodeUnsupported:
                    pass  # keep the batches for the host writer
        if batches is not None:
            import pyarrow as pa
            from .columnar.batch import batch_to_arrow
            tables = [batch_to_arrow(b) for b in batches
                      if int(b.row_count())]
            table = pa.concat_tables(tables) if tables else \
                self.schema.to_arrow().empty_table()
        else:
            table = self.collect()
        return write_table(table, path, fmt, partition_by, mode,
                           **options)

    def cache(self) -> "DataFrame":
        """Cache this query's result as compressed parquet batches
        (ParquetCachedBatchSerializer analog): the first execution
        materializes, later executions on either engine decode the cached
        blobs (device decode where the encoding allows)."""
        from .datasources.cache import CpuCachedExec
        if isinstance(self.plan, CpuCachedExec):
            return self
        codec = self.session.conf.get("spark.rapids.sql.cache.compression")
        return DataFrame(self.session, CpuCachedExec(self.plan, codec))

    def unpersist(self) -> "DataFrame":
        from .datasources.cache import CpuCachedExec
        if isinstance(self.plan, CpuCachedExec):
            self.plan.unpersist()
        return self

    def collect_cpu(self):
        """Execute on the CPU engine only (differential-testing helper)."""
        return self.session.execute_plan(self.plan, use_device=False)

    def explain(self) -> str:
        return self.session.explain_plan(self.plan)

    def __repr__(self):
        return f"DataFrame({self.schema})\n{self.plan.tree_string()}"
