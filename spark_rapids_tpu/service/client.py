"""Worker-process client for the device-owner service.

Every call is deadline-bounded: a wedged service (the axon failure mode —
accepts connections but never answers, or never comes up) surfaces as
DeviceStartupError within `spark.rapids.tpu.device.startupTimeoutSec`
instead of hanging the worker, reusing the round-3 fail-fast contract
(`errors.py` DeviceStartupError; reference `Plugin.scala:436-459`)."""

from __future__ import annotations

import socket
import time
from typing import Dict, Optional, Sequence

from ..errors import AdmissionTimeoutError, DeviceStartupError
from .protocol import ipc_to_table, recv_msg, send_msg

__all__ = ["TpuServiceClient"]


class TpuServiceClient:
    def __init__(self, socket_path: str, deadline_s: float = 60.0):
        self.socket_path = socket_path
        self.deadline_s = deadline_s
        self._sock: Optional[socket.socket] = None

    # ------------------------------------------------------------------
    def connect(self, retry_interval: float = 0.05) -> "TpuServiceClient":
        """Connect + liveness ping under the deadline."""
        t0 = time.monotonic()
        last = "never attempted"
        while time.monotonic() - t0 < self.deadline_s:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(max(self.deadline_s -
                                 (time.monotonic() - t0), 0.05))
                s.connect(self.socket_path)
                self._sock = s
                rep = self._request({"op": "ping"})[0]
                if rep.get("ok"):
                    return self
                last = f"ping not ok: {rep}"
            except DeviceStartupError:
                raise
            except (OSError, ConnectionError) as e:
                last = f"{type(e).__name__}: {e}"
                self._sock = None
                time.sleep(retry_interval)
        raise DeviceStartupError(
            f"device service at {self.socket_path} not answering within "
            f"{self.deadline_s}s ({last})")

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _request(self, header: dict, body: bytes = b""):
        if self._sock is None:
            raise DeviceStartupError("client not connected")
        self._sock.settimeout(self.deadline_s)
        try:
            send_msg(self._sock, header, body)
            return recv_msg(self._sock)
        except socket.timeout:
            raise DeviceStartupError(
                f"device service did not answer {header.get('op')!r} "
                f"within {self.deadline_s}s (wedged service)")

    # ------------------------------------------------------------------
    def acquire(self, timeout: Optional[float] = None) -> int:
        """Block until admitted; returns the global admission order. A
        server-side admission timeout raises AdmissionTimeoutError with the
        held/waiting contention diagnostics from the reply."""
        rep, _ = self._request({"op": "acquire", "timeout": timeout})
        if not rep.get("ok"):
            if rep.get("error_type") == "admission_timeout":
                raise AdmissionTimeoutError(
                    f"device admission not granted within {timeout}s "
                    f"(tokens held: {rep.get('held')}, queue depth: "
                    f"{rep.get('waiting')})",
                    held=rep.get("held", -1), waiting=rep.get("waiting", -1),
                    timeout_s=rep.get("timeout_s"))
            raise TimeoutError(rep.get("error", "admission failed"))
        return rep["order"]

    def release(self) -> None:
        self._request({"op": "release"})

    def run_plan(self, plan_json, paths: Optional[Dict[str, Sequence[str]]]
                 = None, use_device: bool = True):
        """Submit a Spark executedPlan.toJSON; returns a pyarrow Table."""
        rep, body = self._request({"op": "run_plan", "plan": plan_json,
                                   "paths": paths or {},
                                   "use_device": use_device})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("unsupported") or rep.get("error"))
        return ipc_to_table(body)

    def shutdown(self) -> None:
        self._request({"op": "shutdown"})
