"""Worker-process client for the device-owner service.

Every call is deadline-bounded: a wedged service (the axon failure mode —
accepts connections but never answers, or never comes up) surfaces as
DeviceStartupError within `spark.rapids.tpu.device.startupTimeoutSec`
instead of hanging the worker, reusing the round-3 fail-fast contract
(`errors.py` DeviceStartupError; reference `Plugin.scala:436-459`)."""

from __future__ import annotations

import socket
import time
from typing import Dict, Optional, Sequence

from ..errors import (AdmissionTimeoutError, DeadlineExceededError,
                      DeviceStartupError, QueryCancelledError,
                      QueryRejectedError)
from .protocol import ipc_to_table, recv_msg, send_msg

__all__ = ["TpuServiceClient"]


class TpuServiceClient:
    def __init__(self, socket_path: str, deadline_s: float = 60.0):
        self.socket_path = socket_path
        self.deadline_s = deadline_s
        self._sock: Optional[socket.socket] = None

    # ------------------------------------------------------------------
    def connect(self, retry_interval: float = 0.05) -> "TpuServiceClient":
        """Connect + liveness ping under the deadline."""
        t0 = time.monotonic()
        last = "never attempted"
        while time.monotonic() - t0 < self.deadline_s:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(max(self.deadline_s -
                                 (time.monotonic() - t0), 0.05))
                s.connect(self.socket_path)
                self._sock = s
                rep = self._request({"op": "ping"})[0]
                if rep.get("ok"):
                    return self
                last = f"ping not ok: {rep}"
            except DeviceStartupError:
                raise
            except (OSError, ConnectionError) as e:
                last = f"{type(e).__name__}: {e}"
                self._sock = None
                time.sleep(retry_interval)
        raise DeviceStartupError(
            f"device service at {self.socket_path} not answering within "
            f"{self.deadline_s}s ({last})")

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _request(self, header: dict, body: bytes = b""):
        if self._sock is None:
            raise DeviceStartupError("client not connected")
        self._sock.settimeout(self.deadline_s)
        try:
            send_msg(self._sock, header, body)
            return recv_msg(self._sock)
        except socket.timeout:
            raise DeviceStartupError(
                f"device service did not answer {header.get('op')!r} "
                f"within {self.deadline_s}s (wedged service)")

    # ------------------------------------------------------------------
    @staticmethod
    def _raise_typed(rep: dict) -> None:
        """Map a typed error reply onto its exception (errors.py)."""
        et = rep.get("error_type")
        msg = rep.get("error", "service error")
        if et == "rejected":
            raise QueryRejectedError(msg, depth=rep.get("depth", -1))
        if et == "cancelled":
            raise QueryCancelledError(msg,
                                      query_id=rep.get("query_id") or "")
        if et == "deadline":
            raise DeadlineExceededError(msg)

    def acquire(self, timeout: Optional[float] = None,
                priority: int = 0, tenant: Optional[str] = None,
                deadline_s: Optional[float] = None) -> int:
        """Block until admitted; returns the global admission order. A
        server-side admission timeout raises AdmissionTimeoutError with the
        held/waiting contention diagnostics from the reply; a scheduler
        shed/deadline reply raises the matching typed error. priority/
        tenant/deadline_s take effect only on a scheduler-enabled server
        (FIFO servers ignore them)."""
        hdr = {"op": "acquire", "timeout": timeout}
        if priority:
            hdr["priority"] = priority
        if tenant:
            hdr["tenant"] = tenant
        if deadline_s:
            hdr["deadline_s"] = deadline_s
        rep, _ = self._request(hdr)
        if not rep.get("ok"):
            self._raise_typed(rep)
            if rep.get("error_type") == "admission_timeout":
                raise AdmissionTimeoutError(
                    f"device admission not granted within {timeout}s "
                    f"(tokens held: {rep.get('held')}, queue depth: "
                    f"{rep.get('waiting')})",
                    held=rep.get("held", -1), waiting=rep.get("waiting", -1),
                    timeout_s=rep.get("timeout_s"))
            raise TimeoutError(rep.get("error", "admission failed"))
        return rep["order"]

    def release(self) -> None:
        self._request({"op": "release"})

    def run_plan(self, plan_json, paths: Optional[Dict[str, Sequence[str]]]
                 = None, use_device: bool = True,
                 query_id: Optional[str] = None, priority: int = 0,
                 tenant: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        """Submit a Spark executedPlan.toJSON; returns a pyarrow Table.
        `query_id` registers the run for the `cancel` op (issued from a
        DIFFERENT connection); priority/tenant/deadline_s attach the
        scheduling context the engine enforces (typed errors on
        cancel/deadline/shed)."""
        hdr = {"op": "run_plan", "plan": plan_json, "paths": paths or {},
               "use_device": use_device}
        if query_id:
            hdr["query_id"] = query_id
        if priority:
            hdr["priority"] = priority
        if tenant:
            hdr["tenant"] = tenant
        if deadline_s:
            hdr["deadline_s"] = deadline_s
        rep, body = self._request(hdr)
        if not rep.get("ok"):
            self._raise_typed(rep)
            raise RuntimeError(rep.get("unsupported") or rep.get("error"))
        return ipc_to_table(body)

    def cancel(self, query_id: str, priority: Optional[int] = None,
               reason: str = "") -> dict:
        """Kill (default) or — with `priority` — deprioritize an in-flight
        run_plan submitted with that query_id on another connection.
        Returns the server's ack dict; raises on unknown query ids."""
        hdr: dict = {"op": "cancel", "query_id": query_id}
        if priority is not None:
            hdr["priority"] = priority
            hdr["kill"] = False
        if reason:
            hdr["reason"] = reason
        rep, _ = self._request(hdr)
        if not rep.get("ok"):
            raise KeyError(rep.get("error", f"cancel {query_id!r} failed"))
        return rep

    def shutdown(self) -> None:
        self._request({"op": "shutdown"})
