"""Worker-process client for the device-owner service.

Every call is deadline-bounded: a wedged service (the axon failure mode —
accepts connections but never answers, or never comes up) surfaces as
DeviceStartupError within `spark.rapids.tpu.device.startupTimeoutSec`
instead of hanging the worker, reusing the round-3 fail-fast contract
(`errors.py` DeviceStartupError; reference `Plugin.scala:436-459`)."""

from __future__ import annotations

import os
import socket
import time
from typing import Dict, Optional, Sequence

from ..errors import (AdmissionTimeoutError, DeadlineExceededError,
                      DeviceStartupError, QueryCancelledError,
                      QueryRejectedError, ServiceConnectionError)
from .protocol import ipc_to_table, request

__all__ = ["TpuServiceClient"]


class TpuServiceClient:
    """`event_log_dir` (or SPARK_RAPIDS_TPU_CLIENT_EVENTLOG_DIR) makes the
    client write one v2 event-log record per run_plan — the CLIENT half of
    cross-process trace correlation: the record carries the same trace id
    the request header shipped to the server, so
    `profile_report.py --trace` over both processes' logs stitches the
    round trip into one timeline."""

    def __init__(self, socket_path: str, deadline_s: float = 60.0,
                 event_log_dir: Optional[str] = None,
                 event_log_max_bytes: int = 0,
                 event_log_max_files: int = 10):
        self.socket_path = socket_path
        self.deadline_s = deadline_s
        self.event_log_dir = event_log_dir or os.environ.get(
            "SPARK_RAPIDS_TPU_CLIENT_EVENTLOG_DIR") or None
        # same rotation contract as the server's event log (a long-lived
        # worker's log is the same unbounded-growth problem)
        self.event_log_max_bytes = event_log_max_bytes or int(os.environ.get(
            "SPARK_RAPIDS_TPU_CLIENT_EVENTLOG_MAX_BYTES", "0") or 0)
        self.event_log_max_files = event_log_max_files
        self.last_trace_id: Optional[str] = None
        self._sock: Optional[socket.socket] = None

    # ------------------------------------------------------------------
    def connect(self, retry_interval: float = 0.05) -> "TpuServiceClient":
        """Connect + liveness ping under the deadline."""
        t0 = time.monotonic()
        last = "never attempted"
        while time.monotonic() - t0 < self.deadline_s:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(max(self.deadline_s -
                                 (time.monotonic() - t0), 0.05))
                s.connect(self.socket_path)
                self._sock = s
                rep = self._request({"op": "ping"})[0]
                if rep.get("ok"):
                    return self
                last = f"ping not ok: {rep}"
            except DeviceStartupError:
                raise
            except (OSError, ConnectionError) as e:
                last = f"{type(e).__name__}: {e}"
                self._sock = None
                time.sleep(retry_interval)
        raise DeviceStartupError(
            f"device service at {self.socket_path} not answering within "
            f"{self.deadline_s}s ({last})")

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _request(self, header: dict, body: bytes = b""):
        if self._sock is None:
            raise DeviceStartupError("client not connected")
        self._sock.settimeout(self.deadline_s)
        op = header.get("op")
        try:
            return request(self._sock, header, body)
        except socket.timeout:
            raise DeviceStartupError(
                f"device service did not answer {op!r} "
                f"within {self.deadline_s}s (wedged service)")
        except (ConnectionError, OSError) as e:
            # the connection died MID-REQUEST: typed, with the endpoint and
            # op, so failover logic (fleet gateway) and external callers
            # can catch it without pattern-matching raw socket errors. The
            # server releases this connection's admission tokens on the
            # disconnect it just observed — nothing to clean up here.
            self.close()
            raise ServiceConnectionError(
                f"service connection to {self.socket_path} lost during "
                f"{op!r} ({type(e).__name__}: {e})",
                endpoint=self.socket_path, op=op or "",
                phase=getattr(e, "_wire_phase", "recv"), cause=e) from e

    # ------------------------------------------------------------------
    @staticmethod
    def _raise_typed(rep: dict) -> None:
        """Map a typed error reply onto its exception (errors.py)."""
        et = rep.get("error_type")
        msg = rep.get("error", "service error")
        if et == "rejected":
            raise QueryRejectedError(msg, depth=rep.get("depth", -1))
        if et == "cancelled":
            raise QueryCancelledError(msg,
                                      query_id=rep.get("query_id") or "")
        if et == "deadline":
            raise DeadlineExceededError(msg)
        if et == "connection":
            # a fleet gateway reporting that the worker connection died
            # mid-request and the request was not safe to re-dispatch
            raise ServiceConnectionError(
                msg, endpoint=rep.get("endpoint", ""),
                op=rep.get("op", ""), phase=rep.get("phase", "recv"))

    def acquire(self, timeout: Optional[float] = None,
                priority: int = 0, tenant: Optional[str] = None,
                deadline_s: Optional[float] = None,
                trace_id: Optional[str] = None) -> int:
        """Block until admitted; returns the global admission order. A
        server-side admission timeout raises AdmissionTimeoutError with the
        held/waiting contention diagnostics from the reply; a scheduler
        shed/deadline reply raises the matching typed error. priority/
        tenant/deadline_s take effect only on a scheduler-enabled server
        (FIFO servers ignore them)."""
        hdr = {"op": "acquire", "timeout": timeout}
        if priority:
            hdr["priority"] = priority
        if tenant:
            hdr["tenant"] = tenant
        if deadline_s:
            hdr["deadline_s"] = deadline_s
        if trace_id:
            hdr["trace"] = trace_id
        rep, _ = self._request(hdr)
        if not rep.get("ok"):
            self._raise_typed(rep)
            if rep.get("error_type") == "admission_timeout":
                raise AdmissionTimeoutError(
                    f"device admission not granted within {timeout}s "
                    f"(tokens held: {rep.get('held')}, queue depth: "
                    f"{rep.get('waiting')})",
                    held=rep.get("held", -1), waiting=rep.get("waiting", -1),
                    timeout_s=rep.get("timeout_s"))
            raise TimeoutError(rep.get("error", "admission failed"))
        return rep["order"]

    def release(self) -> None:
        self._request({"op": "release"})

    def run_plan(self, plan_json, paths: Optional[Dict[str, Sequence[str]]]
                 = None, use_device: bool = True,
                 query_id: Optional[str] = None, priority: int = 0,
                 tenant: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 trace_id: Optional[str] = None):
        """Submit a Spark executedPlan.toJSON; returns a pyarrow Table.
        `query_id` registers the run for the `cancel` op (issued from a
        DIFFERENT connection); priority/tenant/deadline_s attach the
        scheduling context the engine enforces (typed errors on
        cancel/deadline/shed). A trace id (given or minted, see
        `last_trace_id`) rides the header so the server's profile/flight
        records correlate with this call."""
        from ..utils import spans
        trace = trace_id or spans.current_trace() or spans.new_trace_id()
        self.last_trace_id = trace
        hdr = {"op": "run_plan", "plan": plan_json, "paths": paths or {},
               "use_device": use_device, "trace": trace}
        if query_id:
            hdr["query_id"] = query_id
        if priority:
            hdr["priority"] = priority
        if tenant:
            hdr["tenant"] = tenant
        if deadline_s:
            hdr["deadline_s"] = deadline_s
        t0 = time.monotonic_ns()
        status = "ok"
        try:
            rep, body = self._request(hdr)
            if not rep.get("ok"):
                status = rep.get("error_type") or "error"
                self._raise_typed(rep)
                raise RuntimeError(rep.get("unsupported")
                                   or rep.get("error"))
            return ipc_to_table(body)
        except BaseException:
            if status == "ok":
                status = "error"
            raise
        finally:
            self._log_client_op("run_plan", trace,
                                time.monotonic_ns() - t0, status,
                                query_id=query_id or "")

    def _log_client_op(self, op: str, trace: str, dur_ns: int,
                       status: str, **attrs) -> None:
        """Best-effort client-side event-log record (no event_log_dir =
        no-op; a logging failure never fails the call)."""
        if not self.event_log_dir:
            return
        try:
            from ..utils import spans
            spans.write_client_record(
                self.event_log_dir,
                spans.client_op_record(op, trace, dur_ns, status=status,
                                       socket=self.socket_path, **attrs),
                max_bytes=self.event_log_max_bytes,
                max_files=self.event_log_max_files)
        except Exception:
            pass

    def cancel(self, query_id: str, priority: Optional[int] = None,
               reason: str = "") -> dict:
        """Kill (default) or — with `priority` — deprioritize an in-flight
        run_plan submitted with that query_id on another connection.
        Returns the server's ack dict; raises on unknown query ids."""
        hdr: dict = {"op": "cancel", "query_id": query_id}
        if priority is not None:
            hdr["priority"] = priority
            hdr["kill"] = False
        if reason:
            hdr["reason"] = reason
        rep, _ = self._request(hdr)
        if not rep.get("ok"):
            raise KeyError(rep.get("error", f"cancel {query_id!r} failed"))
        return rep

    def stats(self) -> str:
        """Scrape the server's metrics registry over the socket: returns
        the same Prometheus text the HTTP /metrics endpoint serves.
        Raises RuntimeError when the server runs with telemetry off."""
        rep, body = self._request({"op": "stats"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "stats unavailable"))
        return body.decode("utf-8")

    def cache_stats(self) -> dict:
        """The server's result/fragment-cache accounting (entries, bytes,
        hits/misses/stores per seam, evictions, single-flight waits).
        Raises RuntimeError when the server runs with the cache off."""
        rep, _ = self._request({"op": "cache_stats"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "cache stats unavailable"))
        return rep["stats"]

    def cache_invalidate(self) -> int:
        """Drop every entry in the server's result/fragment cache;
        returns the number dropped. Raises RuntimeError when the server
        runs with the cache off."""
        rep, _ = self._request({"op": "cache_invalidate"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "cache invalidate failed"))
        return rep["dropped"]

    def queries(self) -> dict:
        """The server's live query-introspection snapshot: in-flight
        queries (tenant, current operator, per-operator rows, progress/
        ETA where statistics history exists) plus recently finished
        ones. Against a fleet gateway this is the aggregated fleet view
        with per-worker breaker/draining annotations. Always answers —
        `enabled: false` when the server runs with live off."""
        rep, _ = self._request({"op": "queries"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "queries unavailable"))
        return rep["live"]

    def health(self) -> dict:
        """The server's /healthz snapshot (device init state, admission
        alive probe, heartbeat peers, event-log writability). Works
        regardless of the server's telemetry switch."""
        rep, _ = self._request({"op": "health"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "health unavailable"))
        return rep["health"]

    # ------------------------------------------------ fleet gateway admin
    def drain(self, worker: str, wait_s: Optional[float] = None) -> dict:
        """Mark a fleet worker draining (finish in-flight, route nothing
        new — rolling-restart prep). With `wait_s` the gateway blocks up
        to that long for the worker's in-flight queries to finish and the
        reply reports the remaining count. Gateway-only op."""
        hdr: dict = {"op": "drain", "worker": worker}
        if wait_s is not None:
            hdr["wait_s"] = wait_s
        rep, _ = self._request(hdr)
        if not rep.get("ok"):
            raise KeyError(rep.get("error", f"drain {worker!r} failed"))
        return rep

    def undrain(self, worker: str) -> dict:
        """Return a drained fleet worker to the routable pool."""
        rep, _ = self._request({"op": "undrain", "worker": worker})
        if not rep.get("ok"):
            raise KeyError(rep.get("error", f"undrain {worker!r} failed"))
        return rep

    def fleet_stats(self) -> dict:
        """The gateway's registry snapshot: per-worker breaker state,
        outstanding depth, dispatch/failure counts, draining flags, route
        decisions, and live query placements. Gateway-only op."""
        rep, _ = self._request({"op": "fleet_stats"})
        if not rep.get("ok"):
            raise RuntimeError(rep.get("error", "fleet stats unavailable"))
        return rep["fleet"]

    def shutdown(self) -> None:
        self._request({"op": "shutdown"})
