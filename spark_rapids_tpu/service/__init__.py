"""One-device-owner-per-host service (SURVEY §7 hard part).

TPU chips do not multiplex across processes the way CUDA contexts do: many
Spark executor processes on a host cannot each initialize the backend. The
reference's GpuSemaphore (`GpuSemaphore.scala:67,125`) assumes a shared CUDA
context; here the equivalent is a SERVICE process that owns the chip, with

  * a cross-process admission semaphore (FIFO grants, concurrentGpuTasks
    tokens) that worker processes block on before their data goes
    on-device,
  * a batch ABI across the process boundary: Arrow IPC over a unix-domain
    socket (length-framed JSON header + binary body),
  * plan submission: Spark `executedPlan.toJSON` payloads executed through
    the same translate_spark_plan -> Overrides path as in-process queries —
    which makes this service double as the LIVE transport any external
    Spark can attach to (round-3 verdict items 5 and 8),
  * wedged-service fail-fast: clients bound every connect/response with a
    deadline and raise DeviceStartupError, reusing the round-3 machinery
    (`spark.rapids.tpu.device.startupTimeoutSec`).
"""

from .client import TpuServiceClient
from .server import TpuDeviceService

__all__ = ["TpuDeviceService", "TpuServiceClient"]
