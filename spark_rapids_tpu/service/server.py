"""Device-owner service process (SURVEY §7: one-TPU-service-per-host).

Owns the backend in ONE process and serves worker processes over a
unix-domain socket:

  ping      -> liveness + device identity (clients' fail-fast probe)
  acquire   -> blocks until a cross-process admission token is granted
               (FIFO; `spark.rapids.sql.concurrentGpuTasks` tokens — the
               GpuSemaphore analog across process boundaries,
               `GpuSemaphore.scala:67,125`); reply carries the global
               admission sequence number so tests can assert ordering
  release   -> returns the token (also implicit on disconnect, so a dead
               worker can never leak admission capacity)
  run_plan  -> Spark executedPlan.toJSON + path overrides, executed through
               translate_spark_plan -> Overrides -> engine; result returns
               as an Arrow IPC stream body. This op is the LIVE transport
               seam: any external Spark can ship its executed plan here
               with no code changes on this side.
  shutdown  -> stop serving (tests; production uses process supervision)
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
from typing import Dict, Optional

from .protocol import ipc_to_table, recv_msg, send_msg, table_to_ipc

__all__ = ["TpuDeviceService"]


class _Admission:
    """FIFO cross-process admission semaphore state (server side)."""

    def __init__(self, tokens: int):
        self.tokens = tokens
        self.cv = threading.Condition()
        self.queue = []          # ticket ids, FIFO
        self.holders = set()     # ticket ids currently admitted
        self.order = 0           # global admission sequence
        self.next_ticket = 0

    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Block until admitted; returns the admission sequence number."""
        with self.cv:
            me = self.next_ticket
            self.next_ticket += 1
            self.queue.append(me)
            ok = self.cv.wait_for(
                lambda: self.queue[0] == me and
                len(self.holders) < self.tokens, timeout)
            if not ok:
                self.queue.remove(me)
                self.cv.notify_all()  # unblock whoever is now at the head
                return None
            self.queue.pop(0)
            self.holders.add(me)
            self.order += 1
            self.cv.notify_all()
            return self.order

    def release_one(self, count: int = 1) -> None:
        with self.cv:
            for _ in range(count):
                if self.holders:
                    self.holders.pop()
            self.cv.notify_all()


class TpuDeviceService:
    def __init__(self, conf: Optional[Dict] = None,
                 socket_path: str = "/tmp/spark_rapids_tpu.sock"):
        from ..plugin import TpuSession
        base = {"spark.rapids.sql.enabled": True,
                "spark.rapids.sql.explain": "NONE"}
        base.update(conf or {})
        self.session = TpuSession(base)
        self.socket_path = socket_path
        self.admission = _Admission(self.session.conf.concurrent_tpu_tasks)
        self._stop = threading.Event()
        self._exec_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None

    # ------------------------------------------------------------------
    def serve_forever(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self.session.initialize_device()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.socket_path)
        srv.listen(64)
        srv.settimeout(0.5)
        self._listener = srv
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()
        finally:
            srv.close()
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)

    def _serve_conn(self, conn: socket.socket) -> None:
        held = 0
        try:
            while True:
                try:
                    header, body = recv_msg(conn)
                except (ConnectionError, OSError):
                    return
                op = header.get("op")
                if op == "ping":
                    send_msg(conn, {"ok": True,
                                    "device": self._device_name()})
                elif op == "acquire":
                    try:
                        from .. import faults
                        faults.fire(faults.ADMISSION)
                    except Exception:  # injected admission fault => timeout
                        seq = None
                    else:
                        # real acquire errors must NOT masquerade as
                        # contention — they propagate to the connection
                        # handler like any other server bug
                        seq = self.admission.acquire(
                            timeout=header.get("timeout"))
                    if seq is None:
                        # typed protocol error (errors.py conventions): the
                        # client raises AdmissionTimeoutError carrying the
                        # contention diagnostics captured here
                        with self.admission.cv:
                            n_held = len(self.admission.holders)
                            n_wait = len(self.admission.queue)
                        send_msg(conn, {
                            "ok": False,
                            "error": "admission timeout",
                            "error_type": "admission_timeout",
                            "held": n_held, "waiting": n_wait,
                            "timeout_s": header.get("timeout")})
                    else:
                        held += 1
                        send_msg(conn, {"ok": True, "order": seq})
                elif op == "release":
                    if held:
                        self.admission.release_one()
                        held -= 1
                    send_msg(conn, {"ok": True})
                elif op == "run_plan":
                    self._run_plan(conn, header)
                elif op == "shutdown":
                    send_msg(conn, {"ok": True})
                    self._stop.set()
                    return
                else:
                    send_msg(conn, {"ok": False,
                                    "error": f"unknown op {op!r}"})
        finally:
            # a worker that dies holding tokens must not leak capacity
            if held:
                self.admission.release_one(held)
            conn.close()

    # ------------------------------------------------------------------
    def _device_name(self) -> str:
        try:
            import jax
            return str(jax.devices()[0])
        except Exception as e:  # pragma: no cover
            return f"<no device: {e}>"

    def _run_plan(self, conn: socket.socket, header: dict) -> None:
        from ..integration.spark_plan import (UnsupportedSparkPlan,
                                              translate_spark_plan)
        try:
            plan = translate_spark_plan(header["plan"], self.session.conf,
                                        header.get("paths") or {})
            use_device = bool(header.get("use_device", True))
            with self._exec_lock:
                table = self.session.execute_plan(plan,
                                                  use_device=use_device)
            send_msg(conn, {"ok": True, "num_rows": table.num_rows},
                     table_to_ipc(table))
        except UnsupportedSparkPlan as e:
            send_msg(conn, {"ok": False, "unsupported": str(e)})
        except Exception as e:
            send_msg(conn, {"ok": False,
                            "error": f"{type(e).__name__}: {e}"})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket", default="/tmp/spark_rapids_tpu.sock")
    ap.add_argument("--conf", action="append", default=[],
                    metavar="K=V")
    ap.add_argument("--platform", default=None,
                    help="force jax platform (tests: cpu)")
    args = ap.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax
        jax.config.update("jax_platforms", args.platform)
    conf = {}
    for kv in args.conf:
        k, _, v = kv.partition("=")
        conf[k] = json.loads(v) if v and v[0] in "[{0123456789tf-" else v
    svc = TpuDeviceService(conf, args.socket)
    svc.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
