"""Device-owner service process (SURVEY §7: one-TPU-service-per-host).

Owns the backend in ONE process and serves worker processes over a
unix-domain socket:

  ping      -> liveness + device identity (clients' fail-fast probe)
  acquire   -> blocks until a cross-process admission token is granted
               (`spark.rapids.sql.concurrentGpuTasks` tokens — the
               GpuSemaphore analog across process boundaries,
               `GpuSemaphore.scala:67,125`); reply carries the global
               admission sequence number so tests can assert ordering.
               FIFO by default; with spark.rapids.tpu.sched.enabled the
               header's priority/tenant/deadline_s drive the priority-
               weighted fair queue (sched/scheduler.py) with load
               shedding. Queued waiters whose client dies are REMOVED
               (socket-EOF probe per wait slice) — a dead client must
               not be granted a token nobody will return.
  release   -> returns the token (also implicit on disconnect, so a dead
               worker can never leak admission capacity)
  run_plan  -> Spark executedPlan.toJSON + path overrides, executed through
               translate_spark_plan -> Overrides -> engine; result returns
               as an Arrow IPC stream body. Optional header fields
               query_id/priority/tenant/deadline_s attach a scheduling
               context; a cancelled/expired query replies with the typed
               error_type instead of a result. This op is the LIVE
               transport seam: any external Spark can ship its executed
               plan here with no code changes on this side.
  cancel    -> kill or deprioritize an in-flight (or queued) run_plan by
               query_id from ANOTHER connection: `kill` (default) cancels
               its CancelToken — the engine unwinds at the next
               cooperative cancellation point; `priority` reassigns the
               context's priority for its future admissions.
  queries   -> live query-introspection snapshot (live.snapshot()): the
               in-flight registry with progress/ETA plus recent queries;
               answers enabled:false when live introspection is off
  cache_stats      -> result/fragment-cache accounting (rescache.stats())
  cache_invalidate -> drop every cached result/fragment (out-of-band data
               rewrites the file-identity keys cannot observe)
  shutdown  -> stop serving (tests; production uses process supervision)
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time
from typing import Dict, Optional

from ..errors import (DeadlineExceededError, QueryCancelledError,
                      QueryRejectedError)
from ..sched import (ABANDONED, AdmissionQueue, QueryContext,
                     parse_tenant_map)
from .protocol import recv_msg, send_msg, table_to_ipc

__all__ = ["TpuDeviceService"]

_PROC_START_TS = time.time()


class _Admission:
    """Cross-process admission semaphore state (server side), backed by the
    shared sched.AdmissionQueue. With the scheduler disabled every request
    enters at equal priority/weight, which the queue serves in strict
    arrival order — the original FIFO contract, byte-for-byte."""

    def __init__(self, tokens: int, conf=None):
        sched_on = bool(conf is not None and
                        conf.get("spark.rapids.tpu.sched.enabled"))
        weights = parse_tenant_map(
            conf.get("spark.rapids.tpu.sched.tenant.weights")) \
            if sched_on else None
        wait_ms = conf.get("spark.rapids.tpu.sched.maxQueueWaitMs") \
            if sched_on else 0
        self.sched_enabled = sched_on
        self.queue = AdmissionQueue(
            tokens,
            weights=weights,
            max_depth=(conf.get("spark.rapids.tpu.sched.maxQueueDepth")
                       if sched_on else 0),
            max_wait_s=wait_ms / 1000.0 if wait_ms else 0.0)

    def acquire(self, timeout: Optional[float] = None, priority: int = 0,
                tenant: str = "default", token=None, alive=None):
        """Block until admitted; returns the admission sequence number,
        None on timeout, ABANDONED when the client died while queued.
        Scheduler-off forces FIFO inputs so policy cannot leak in."""
        if not self.sched_enabled:
            priority, tenant, token = 0, "default", None
        return self.queue.acquire(priority=priority, tenant=tenant,
                                  timeout=timeout, token=token, alive=alive)

    def release_one(self, count: int = 1) -> None:
        self.queue.release(count)

    def snapshot(self):
        """(held, waiting) contention diagnostics for error replies."""
        with self.queue.cv:
            return self.queue.holders, self.queue._depth_locked()


def _conn_alive(conn: socket.socket) -> bool:
    """Non-consuming liveness probe: a queued waiter polls this per wait
    slice so a client that died while PARKED in the admission queue is
    removed instead of eventually being granted a token to a closed
    socket. MSG_PEEK never consumes — a pipelined next request (data
    present) still reads normally afterwards."""
    try:
        data = conn.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT)
    except (BlockingIOError, InterruptedError):
        return True  # alive, nothing buffered
    except OSError:
        return False
    return len(data) > 0  # b'' = orderly shutdown


class TpuDeviceService:
    def __init__(self, conf: Optional[Dict] = None,
                 socket_path: str = "/tmp/spark_rapids_tpu.sock"):
        from ..plugin import TpuSession
        base = {"spark.rapids.sql.enabled": True,
                "spark.rapids.sql.explain": "NONE"}
        base.update(conf or {})
        self.session = TpuSession(base)
        self.socket_path = socket_path
        self.admission = _Admission(self.session.conf.concurrent_tpu_tasks,
                                    self.session.conf)
        self._stop = threading.Event()
        self._exec_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        # in-flight/queued run_plan contexts by query_id (cancel op target)
        self._queries: Dict[str, QueryContext] = {}
        self._queries_mu = threading.Lock()

    # ------------------------------------------------------------------
    def serve_forever(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self.session.initialize_device()
        # arm the in-process admission door with THIS service's conf:
        # DeviceManager.initialize is once-per-process, so a process that
        # already initialized a device through another session would
        # otherwise leave a sched-enabled service silently admitting
        # run_plans through a stale FIFO semaphore
        from ..memory.semaphore import TpuSemaphore
        TpuSemaphore.initialize(self.session.conf.concurrent_tpu_tasks,
                                self.session.conf)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.socket_path)
        srv.listen(64)
        srv.settimeout(0.5)
        self._listener = srv
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()
        finally:
            srv.close()
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)

    def _serve_conn(self, conn: socket.socket) -> None:
        held = 0
        try:
            while True:
                try:
                    header, body = recv_msg(conn)
                except (ConnectionError, OSError):
                    return
                op = header.get("op")
                if op == "ping":
                    # pid + start time let the fleet registry tell a
                    # RESTARTED worker from a recovered one (reincarnation
                    # reconciliation: purge stale placements, count it)
                    send_msg(conn, {"ok": True,
                                    "device": self._device_name(),
                                    "pid": os.getpid(),
                                    "started_ts": _PROC_START_TS})
                elif op == "acquire":
                    seq = self._handle_acquire(conn, header)
                    if seq is ABANDONED:
                        return  # client died while queued
                    if seq is not None:
                        # count the hold BEFORE the reply: a send that
                        # fails to a just-dead client must still release
                        # this token in the finally below
                        held += 1
                        send_msg(conn, {"ok": True, "order": seq})
                elif op == "release":
                    if held:
                        self.admission.release_one()
                        held -= 1
                    send_msg(conn, {"ok": True})
                elif op == "run_plan":
                    self._run_plan(conn, header)
                elif op == "cancel":
                    self._handle_cancel(conn, header)
                elif op == "stats":
                    self._handle_stats(conn)
                elif op == "health":
                    self._handle_health(conn)
                elif op == "queries":
                    self._handle_queries(conn)
                elif op == "cache_stats":
                    self._handle_cache_stats(conn)
                elif op == "cache_invalidate":
                    self._handle_cache_invalidate(conn)
                elif op == "shutdown":
                    send_msg(conn, {"ok": True})
                    self._stop.set()
                    return
                else:
                    send_msg(conn, {"ok": False,
                                    "error": f"unknown op {op!r}"})
        finally:
            # a worker that dies holding tokens must not leak capacity
            if held:
                self.admission.release_one(held)
            conn.close()

    def _handle_acquire(self, conn: socket.socket, header: dict):
        """One acquire op. Returns the admission order on grant (caller
        records the hold, then replies), ABANDONED when the client died
        while queued (caller unwinds), or None after a non-grant reply
        (timeout/shed/deadline) was already sent."""
        from .. import faults, telemetry
        token = None
        deadline_s = header.get("deadline_s")
        if deadline_s:
            from ..sched import CancelToken
            token = CancelToken(deadline_s)
        telemetry.flight("service", "acquire",
                         trace_id=header.get("trace") or "",
                         tenant=header.get("tenant") or "default",
                         priority=int(header.get("priority") or 0))
        try:
            try:
                faults.fire(faults.ADMISSION)
            except Exception:  # injected admission fault => timeout
                seq = None
            else:
                # real acquire errors must NOT masquerade as contention —
                # they propagate to the connection handler like any other
                # server bug (the typed shed/deadline errors are caught
                # below and become typed protocol replies)
                seq = self.admission.acquire(
                    timeout=header.get("timeout"),
                    priority=int(header.get("priority") or 0),
                    tenant=header.get("tenant") or "default",
                    token=token,
                    alive=lambda: _conn_alive(conn))
        except QueryRejectedError as e:
            held, waiting = self.admission.snapshot()
            send_msg(conn, {"ok": False, "error": str(e),
                            "error_type": "rejected",
                            "depth": e.depth, "held": held,
                            "waiting": waiting})
            return None
        except DeadlineExceededError as e:
            send_msg(conn, {"ok": False, "error": str(e),
                            "error_type": "deadline"})
            return None
        if seq is ABANDONED:
            return ABANDONED
        if seq is None:
            # typed protocol error (errors.py conventions): the client
            # raises AdmissionTimeoutError carrying the contention
            # diagnostics captured here
            held, waiting = self.admission.snapshot()
            send_msg(conn, {
                "ok": False,
                "error": "admission timeout",
                "error_type": "admission_timeout",
                "held": held, "waiting": waiting,
                "timeout_s": header.get("timeout")})
            return None
        return seq

    def _handle_cancel(self, conn: socket.socket, header: dict) -> None:
        qid = header.get("query_id")
        with self._queries_mu:
            ctx = self._queries.get(qid)
        if ctx is None:
            send_msg(conn, {"ok": False,
                            "error": f"unknown query_id {qid!r}",
                            "error_type": "unknown_query"})
            return
        new_priority = header.get("priority")
        if new_priority is not None:
            ctx.priority = int(new_priority)  # deprioritize, keep running
        if header.get("kill", new_priority is None):
            ctx.token.cancel(header.get("reason")
                             or f"cancel op for {qid}")
        send_msg(conn, {"ok": True, "query_id": qid,
                        "killed": bool(header.get(
                            "kill", new_priority is None)),
                        "priority": ctx.priority})

    def _handle_stats(self, conn: socket.socket) -> None:
        """`stats` op: the Prometheus text scrape as the reply BODY — a
        client that only reaches the server by socket polls the same
        families the HTTP /metrics endpoint serves."""
        from .. import telemetry
        if not telemetry.is_enabled():
            send_msg(conn, {
                "ok": False,
                "error": "telemetry disabled "
                         "(spark.rapids.tpu.telemetry.enabled)",
                "error_type": "telemetry_disabled"})
            return
        body = telemetry.render_prometheus().encode("utf-8")
        send_msg(conn, {"ok": True, "lines": len(body.splitlines())}, body)

    def _handle_health(self, conn: socket.socket) -> None:
        """`health` op: the /healthz snapshot (device init state,
        admission-door alive probe, heartbeat-known peers, event-log
        writability). Answers regardless of the telemetry switch — a
        liveness probe that needs a conf flag to answer is useless."""
        from ..telemetry import health_snapshot
        snap = health_snapshot(self.session.conf)
        send_msg(conn, {"ok": True, "health": snap})

    def _handle_queries(self, conn: socket.socket) -> None:
        """`queries` op: the live-introspection snapshot (in-flight
        queries with progress/ETA plus the recent ring). Always answers
        ok — `enabled: false` with empty lists when
        spark.rapids.tpu.live.enabled is off, so a fleet fan-out over
        mixed-config workers degrades per slot instead of erroring."""
        from .. import live
        send_msg(conn, {"ok": True, "live": live.snapshot()})

    def _handle_cache_stats(self, conn: socket.socket) -> None:
        """`cache_stats` op: the result/fragment cache's lifetime
        accounting (entries/bytes/hits/misses/evictions per seam)."""
        from .. import rescache
        snap = rescache.stats()
        if snap is None:
            send_msg(conn, {
                "ok": False,
                "error": "result cache disabled "
                         "(spark.rapids.tpu.rescache.enabled)",
                "error_type": "rescache_disabled"})
            return
        send_msg(conn, {"ok": True, "stats": snap})

    def _handle_cache_invalidate(self, conn: socket.socket) -> None:
        """`cache_invalidate` op: drop every cached result/fragment (an
        operator's big hammer after an out-of-band data rewrite the
        file-identity keys cannot see, e.g. an in-place object-store
        overwrite preserving mtime)."""
        from .. import rescache
        if not rescache.is_enabled():
            send_msg(conn, {
                "ok": False,
                "error": "result cache disabled "
                         "(spark.rapids.tpu.rescache.enabled)",
                "error_type": "rescache_disabled"})
            return
        send_msg(conn, {"ok": True, "dropped": rescache.invalidate()})

    def _concurrent_ok(self) -> bool:
        """Scheduled run_plans may execute concurrently only when the
        server conf runs the scheduler (the admission door that orders
        them) AND the per-query observability that assumes serial
        execution is off: the query profiler (QueryProfile's active slot
        is process-wide, utils/spans.py) and DEBUG metrics (the
        peakDevMemory watermark is a per-query reset of the process
        MemoryBudget — overlapping queries would erase/inflate each
        other's peaks)."""
        conf = self.session.conf
        return self.admission.sched_enabled and not (
            conf.get("spark.rapids.tpu.metrics.eventLog.dir")
            or conf.get("spark.rapids.tpu.metrics.profile.enabled")
            or conf.get("spark.rapids.sql.metrics.level") == "DEBUG")

    # ------------------------------------------------------------------
    def _device_name(self) -> str:
        try:
            import jax
            return str(jax.devices()[0])
        except Exception as e:  # pragma: no cover
            return f"<no device: {e}>"

    def _run_plan(self, conn: socket.socket, header: dict) -> None:
        from ..integration.spark_plan import (UnsupportedSparkPlan,
                                              translate_spark_plan)
        ctx = None
        qid = header.get("query_id")
        trace = header.get("trace") or None
        if qid or header.get("priority") or header.get("tenant") \
                or header.get("deadline_s"):
            ctx = QueryContext(
                tenant=header.get("tenant") or "default",
                priority=int(header.get("priority") or 0),
                deadline_s=header.get("deadline_s"),
                query_id=qid,
                trace_id=trace)
            if qid:
                with self._queries_mu:
                    self._queries[qid] = ctx
        try:
            plan = translate_spark_plan(header["plan"], self.session.conf,
                                        header.get("paths") or {})
            use_device = bool(header.get("use_device", True))
            if ctx is not None:
                ctx.token.check()  # cancelled while translating?
            if ctx is not None and self._concurrent_ok():
                # a SCHEDULER-ENABLED server does not serialize scheduled
                # run_plans on _exec_lock: a plain lock is scheduler-blind
                # (arbitrary wakeup order would bury a high-priority query
                # behind queued low-priority ones and park cancels/
                # deadlines until the lock was won). The engine admits the
                # query at its start through the scheduler door (priority/
                # fair-share/shed, cancel-aware waits) and releases at
                # query end, so concurrency stays bounded by
                # concurrentGpuTasks.
                table = self.session.execute_plan(plan,
                                                  use_device=use_device,
                                                  sched_ctx=ctx,
                                                  trace_id=trace)
            else:
                # scheduler-off servers keep the historical one-at-a-time
                # execution even for context-carrying requests ('FIFO
                # servers ignore them' — the scheduling fields only add
                # cancelability/deadlines, observed before the lock and
                # at every engine checkpoint once running). Ditto when
                # the profiler is active: its per-query state is a
                # process-wide single slot, so overlapping queries would
                # cross-attribute spans.
                with self._exec_lock:
                    table = self.session.execute_plan(
                        plan, use_device=use_device, sched_ctx=ctx,
                        trace_id=trace)
            send_msg(conn, {"ok": True, "num_rows": table.num_rows},
                     table_to_ipc(table))
        except UnsupportedSparkPlan as e:
            send_msg(conn, {"ok": False, "unsupported": str(e)})
        except QueryCancelledError as e:
            send_msg(conn, {"ok": False, "error": str(e),
                            "error_type": "cancelled", "query_id": qid})
        except DeadlineExceededError as e:
            send_msg(conn, {"ok": False, "error": str(e),
                            "error_type": "deadline", "query_id": qid})
        except QueryRejectedError as e:
            send_msg(conn, {"ok": False, "error": str(e),
                            "error_type": "rejected", "query_id": qid})
        except Exception as e:
            send_msg(conn, {"ok": False,
                            "error": f"{type(e).__name__}: {e}"})
        finally:
            if qid:
                with self._queries_mu:
                    # only unregister OUR context: a resubmitted run_plan
                    # reusing the query_id overwrote the entry, and the
                    # first finisher must not strip the survivor's cancel
                    # handle
                    if self._queries.get(qid) is ctx:
                        del self._queries[qid]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket", default="/tmp/spark_rapids_tpu.sock")
    ap.add_argument("--conf", action="append", default=[],
                    metavar="K=V")
    ap.add_argument("--platform", default=None,
                    help="force jax platform (tests: cpu)")
    args = ap.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax
        jax.config.update("jax_platforms", args.platform)
    conf = {}
    for kv in args.conf:
        k, _, v = kv.partition("=")
        conf[k] = json.loads(v) if v and v[0] in "[{0123456789tf-" else v
    svc = TpuDeviceService(conf, args.socket)
    svc.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
