"""Wire protocol for the device-owner service: length-framed JSON header +
optional binary body (Arrow IPC stream) over a stream socket.

Frame layout (little-endian):
    u32 header_len | header (UTF-8 JSON) | u64 body_len | body bytes

Kept deliberately dumb — the interesting contracts (admission FIFO, plan
translation, Arrow batch ABI) live above it, and any transport that can
move these two buffers (TCP, shared memory ring, Spark RPC) can replace
the socket without touching either end's logic."""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

_HDR = struct.Struct("<I")
_BODY = struct.Struct("<Q")
MAX_HEADER = 64 * 1024 * 1024
MAX_BODY = 1 << 40


def send_msg(sock: socket.socket, header: dict,
             body: bytes = b"") -> None:
    hb = json.dumps(header).encode("utf-8")
    sock.sendall(_HDR.pack(len(hb)) + hb + _BODY.pack(len(body)))
    if body:
        sock.sendall(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    (hlen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if hlen > MAX_HEADER:
        raise ConnectionError(f"header too large: {hlen}")
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    (blen,) = _BODY.unpack(_recv_exact(sock, _BODY.size))
    if blen > MAX_BODY:
        raise ConnectionError(f"body too large: {blen}")
    body = _recv_exact(sock, blen) if blen else b""
    return header, body


def request(sock: socket.socket, header: dict,
            body: bytes = b"") -> Tuple[dict, bytes]:
    """One request/reply round trip (send + recv). Shared by the worker
    client and the fleet gateway's dispatch path so both surface the same
    failure anatomy: OSError/ConnectionError from inside is tagged with
    the phase it died in (`e._wire_phase` = "send"/"recv") — the gateway's
    retry-safety rule for write plans hangs off that distinction."""
    try:
        send_msg(sock, header, body)
    except (ConnectionError, OSError) as e:
        e._wire_phase = "send"
        raise
    try:
        return recv_msg(sock)
    except (ConnectionError, OSError) as e:
        e._wire_phase = "recv"
        raise


def table_to_ipc(table) -> bytes:
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(buf: bytes):
    import pyarrow as pa
    with pa.ipc.open_stream(pa.BufferReader(buf)) as r:
        return r.read_all()
