"""Per-query scheduling context: tenant, priority, deadline, cancellation.

A `QueryContext` is installed for the duration of one query
(`plugin.TpuSession` activates it; the device service builds one per
`run_plan` from the request header). Its `CancelToken` is the single
cooperative-cancellation channel: every blocking or long-running seam in
the engine — exec batch pulls, prefetch producer loops, OOM-retry
backoff, shuffle fetch retry sleeps, admission queue waits — calls
`checkpoint()` (or checks the token directly) and unwinds with a typed
`QueryCancelledError`/`DeadlineExceededError` when the query was
cancelled or ran past its deadline.

Disabled-path contract (mirrors faults._ACTIVE): when no context is
active anywhere in the process, `checkpoint()` is ONE module-global int
read — queries that never opt into scheduling pay nothing."""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional

from ..errors import DeadlineExceededError, QueryCancelledError

__all__ = ["CancelToken", "QueryContext", "activate", "adopt", "checkpoint",
           "current", "current_tenant", "remaining_deadline_s", "suspend"]

_tls = threading.local()
_lock = threading.Lock()
# count of activate() scopes currently open process-wide; 0 => checkpoint()
# and current() return immediately (one global read, no thread-local touch)
_ACTIVE = 0


class CancelToken:
    """Cooperative cancellation + deadline for one query.

    `cancel()` may be called from ANY thread (another connection's
    `cancel` op, a timeout supervisor); the query's own threads observe it
    at their next `check()`. Registered waiters (the admission queue) are
    poked so a parked query wakes immediately instead of at its next wait
    slice."""

    __slots__ = ("deadline_s", "deadline_ns", "_cancelled", "_reason",
                 "_mu", "_waiters")

    def __init__(self, deadline_s: Optional[float] = None):
        # the configured DURATION, kept for diagnostics (deadline_ns is an
        # absolute monotonic instant, meaningless outside this process)
        self.deadline_s = (float(deadline_s)
                           if deadline_s and deadline_s > 0 else None)
        self.deadline_ns = (time.monotonic_ns() + int(deadline_s * 1e9)
                            if deadline_s and deadline_s > 0 else None)
        self._cancelled = False
        self._reason = ""
        self._mu = threading.Lock()
        self._waiters: List[Callable[[], None]] = []

    # -- cancel side -------------------------------------------------------
    def cancel(self, reason: str = "cancelled") -> None:
        with self._mu:
            if self._cancelled:
                return
            self._cancelled = True
            self._reason = reason
            waiters = list(self._waiters)
        for wake in waiters:
            try:
                wake()
            except Exception:
                pass

    def add_waiter(self, wake: Callable[[], None]) -> None:
        with self._mu:
            self._waiters.append(wake)

    def remove_waiter(self, wake: Callable[[], None]) -> None:
        with self._mu:
            if wake in self._waiters:
                self._waiters.remove(wake)

    # -- observe side ------------------------------------------------------
    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def reason(self) -> str:
        return self._reason

    @property
    def expired(self) -> bool:
        return (self.deadline_ns is not None
                and time.monotonic_ns() >= self.deadline_ns)

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None = no deadline; 0.0 = expired)."""
        if self.deadline_ns is None:
            return None
        return max((self.deadline_ns - time.monotonic_ns()) / 1e9, 0.0)

    @property
    def status(self) -> str:
        """'ok' | 'cancelled' | 'deadline' — the profile-record status."""
        if self._cancelled:
            return "cancelled"
        if self.expired:
            return "deadline"
        return "ok"

    def check(self) -> None:
        """Raise the typed error if cancelled or past the deadline."""
        if self._cancelled:
            raise QueryCancelledError(
                f"query cancelled: {self._reason}")
        if self.expired:
            raise DeadlineExceededError(
                f"query deadline of {self.deadline_s}s exceeded",
                deadline_s=self.deadline_s)


class QueryContext:
    """One query's scheduling identity: tenant, priority, deadline, token."""

    _qid_counter = itertools.count(1)

    def __init__(self, tenant: str = "default", priority: int = 0,
                 deadline_s: Optional[float] = None,
                 token: Optional[CancelToken] = None,
                 query_id: Optional[str] = None,
                 trace_id: Optional[str] = None):
        self.tenant = tenant or "default"
        self.priority = int(priority)
        self.token = token or CancelToken(deadline_s)
        self.query_id = query_id or f"q{next(QueryContext._qid_counter)}"
        # cross-process trace correlation: a service run_plan header's
        # trace id lands here and plugin.TpuSession scopes it around the
        # query, so server-side profile/flight records share the client's
        # id. None = the session mints one at query start.
        self.trace_id = trace_id


def current() -> Optional[QueryContext]:
    if not _ACTIVE:
        return None
    return getattr(_tls, "ctx", None)


def current_tenant() -> Optional[str]:
    """Tenant of the active context, None when no context is active (the
    budget's tenant ledger stays untouched for unscheduled work)."""
    ctx = current()
    return ctx.tenant if ctx is not None else None


def checkpoint() -> None:
    """The engine-wide cancellation point: raises the active context's
    typed error, or returns immediately (one global read) when no context
    is active."""
    if not _ACTIVE:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.token.check()


def remaining_deadline_s() -> Optional[float]:
    """Remaining seconds of the active context's deadline; None when no
    context or no deadline. Backoff sleeps clamp to this (a retrying fetch
    must not outlive its query's deadline)."""
    ctx = current()
    if ctx is None:
        return None
    return ctx.token.remaining_s()


class activate:
    """Install `ctx` as this thread's query context for a scope.

    Re-entrant across nested execute_plan calls (adaptive stages): the
    previous context is restored on exit."""

    def __init__(self, ctx: QueryContext):
        self._ctx = ctx
        self._prev: Optional[QueryContext] = None

    def __enter__(self) -> QueryContext:
        global _ACTIVE
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        with _lock:
            _ACTIVE += 1
        return self._ctx

    def __exit__(self, *exc) -> bool:
        global _ACTIVE
        _tls.ctx = self._prev
        with _lock:
            _ACTIVE -= 1
        return False


def adopt(ctx: Optional[QueryContext]) -> None:
    """Attach an existing context to the CURRENT thread without opening a
    new activation scope — the prefetch-producer pattern (the owning
    consumer thread holds the activation; the producer merely observes the
    same token, exactly like it adopts the task's TaskMetrics and
    semaphore hold). No-op for None."""
    if ctx is not None:
        _tls.ctx = ctx


class suspend:
    """Detach this thread's context for a scope: work inside runs with NO
    active tenant/token attribution, restored on exit. The rescache parks
    shared fragments under this — a cross-query cache entry belongs to no
    tenant, so its park-time charge must not pin one query's sub-quota
    ledger until some later eviction. Does not change the _ACTIVE scope
    count (other threads' contexts are untouched)."""

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = None
        return self

    def __exit__(self, *exc) -> bool:
        _tls.ctx = self._prev
        return False
