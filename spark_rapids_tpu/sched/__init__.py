"""Query scheduler — multi-tenant admission control, priorities, deadlines,
and cooperative cancellation (ARCHITECTURE.md "Query scheduler").

The reference's only concurrency control is a fixed-width FIFO
(`GpuSemaphore.scala`, spark.rapids.sql.concurrentGpuTasks). Serving-scale
engines (Theseus, arXiv:2508.05029; "Rethinking Analytical Processing in
the GPU Era", arXiv:2508.04701) are gated by scheduling policy, not
kernels: the engine needs to decide *which* query gets the device, for
*how long*, and what happens to everyone else under overload. This package
owns every path onto the device:

  * `context.py` — `QueryContext`/`CancelToken`: per-query tenant,
    priority, deadline and cooperative cancellation, threaded through the
    exec pull loops, prefetch threads, OOM-retry backoff and shuffle fetch
    retry via the near-free `checkpoint()` hook.
  * `scheduler.py` — `AdmissionQueue`: the priority + weighted-fair
    admission core shared by the in-process `TpuSemaphore` and the
    cross-process service `_Admission`, with queue-depth/wait load
    shedding (`QueryRejectedError`) and the `sched.admit` fault point.

`spark.rapids.tpu.sched.enabled=false` (the default) keeps the exact FIFO
paths: `TpuSemaphore` stays on its `threading.BoundedSemaphore`, no
contexts activate, no new threads exist anywhere in this package (the
scheduler never spawns any), and `checkpoint()` is one module-global int
read."""

from .context import (CancelToken, QueryContext, activate, adopt,
                      checkpoint, current, current_tenant,
                      remaining_deadline_s)
from .scheduler import (ABANDONED, AdmissionQueue, QueryScheduler,
                        parse_tenant_map)

PRIORITY_LOW = -10
PRIORITY_NORMAL = 0
PRIORITY_HIGH = 10

__all__ = ["CancelToken", "QueryContext", "activate", "adopt", "checkpoint",
           "current", "current_tenant", "remaining_deadline_s",
           "AdmissionQueue", "QueryScheduler", "ABANDONED",
           "parse_tenant_map",
           "PRIORITY_LOW", "PRIORITY_NORMAL", "PRIORITY_HIGH"]
