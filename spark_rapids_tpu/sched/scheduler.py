"""Priority-weighted fair admission queue + the process query scheduler.

`AdmissionQueue` is the one admission policy engine for both device
doors: `memory/semaphore.py TpuSemaphore` (in-process task admission,
the GpuSemaphore analog) and `service/server.py _Admission` (the
cross-process token pool). Policy:

  * **Priority first** — a waiter with higher `priority` is always
    granted before any lower-priority waiter, regardless of arrival
    order (strict priority; the stress suite asserts no inversion).
  * **Weighted fair within a priority** — stride scheduling over
    tenants: each grant advances the tenant's virtual pass by
    `STRIDE / weight`, and the waiter whose tenant has the LOWEST pass
    wins, so a tenant with weight 4 is admitted ~4x as often as a
    weight-1 tenant under sustained contention. A tenant joining (or
    rejoining) starts at the current queue-minimum pass, never at 0 —
    an idle tenant cannot bank credit.
  * **FIFO as the degenerate case** — equal priorities and weights
    reduce selection to arrival order, which is how the queue serves
    the scheduler-disabled service path byte-for-byte.
  * **Load shedding** — depth beyond `max_depth` rejects at enqueue;
    waiting past `max_wait_s` rejects in place; both raise the typed
    `QueryRejectedError` and the query never touches the device.
  * **Deadlines + cancellation** — a waiter parked past its token's
    deadline (or cancelled from another thread) unwinds with the typed
    error; `cancel()` pokes the condition so the wake is immediate.
  * **Abandonment** — an optional `alive` callback (the service's
    socket-EOF probe) is polled per wait slice so a DEAD client's
    queued waiter is REMOVED instead of being granted a token nobody
    will return (the release-on-disconnect fix for queued waiters).

The `sched.admit` fault point fires on every acquire; an injected
failure degrades to the typed `QueryRejectedError` — admission faults
must shed, never crash the server loop or grant untracked tokens."""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from .. import faults, telemetry
from ..errors import (DeadlineExceededError, QueryCancelledError,
                      QueryRejectedError)
from . import context as _ctx

__all__ = ["AdmissionQueue", "QueryScheduler", "ABANDONED",
           "parse_tenant_map", "live_admission_queues"]

# returned by acquire() when the `alive` probe said the caller is gone
ABANDONED = object()

_STRIDE = 1 << 20

# every constructed AdmissionQueue, weakly — the telemetry depth/holders
# gauges and the healthz alive probe walk the LIVE ones without the
# telemetry layer having to know which doors exist (in-process semaphore,
# service _Admission, tests)
_LIVE_QUEUES: "weakref.WeakSet" = weakref.WeakSet()


def live_admission_queues() -> List["AdmissionQueue"]:
    return list(_LIVE_QUEUES)


def parse_tenant_map(spec: str) -> Dict[str, float]:
    """Parse `tenantA=4,tenantB=1` specs (weights and quota fractions share
    the grammar)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, v = part.partition("=")
        if not sep or not k.strip():
            raise ValueError(f"bad tenant map entry {part!r} (want k=v)")
        out[k.strip()] = float(v)
    return out


class _Waiter:
    __slots__ = ("seq", "priority", "tenant", "granted", "order")

    def __init__(self, seq: int, priority: int, tenant: str):
        self.seq = seq
        self.priority = priority
        self.tenant = tenant
        self.granted = False
        self.order = 0


class AdmissionQueue:
    """Admission token pool with the policy above. Thread-safe; spawns no
    threads of its own (waiters park on one condition variable)."""

    # wait slice while an `alive` liveness probe must be polled (the probe
    # has no callback channel, unlike cancel tokens which wake the cv
    # directly). Coarse on purpose: every parked waiter wakes and issues
    # one MSG_PEEK syscall per slice under the queue lock, so the slice
    # trades dead-client detection latency against lock churn at depth.
    # Plain waits (no probe) block for the full computed timeout.
    ALIVE_POLL_S = 0.25

    def __init__(self, tokens: int,
                 weights: Optional[Dict[str, float]] = None,
                 max_depth: int = 0, max_wait_s: float = 0.0):
        self.tokens = tokens
        self.weights = dict(weights or {})
        self.max_depth = max_depth
        self.max_wait_s = max_wait_s
        self.cv = threading.Condition()
        self.holders = 0
        self.order = 0            # global admission sequence (diagnostics)
        self._seq = 0
        self._waiters: List[_Waiter] = []
        self._tenant_pass: Dict[str, float] = {}
        # observability: deepest queue ever seen + lifetime shed count
        self.peak_depth = 0
        self.shed_count = 0
        _LIVE_QUEUES.add(self)

    # ------------------------------------------------------------------
    def _depth_locked(self) -> int:
        """Waiters actually QUEUED: granted ones still in the list are
        merely between their grant and their thread waking to depart —
        counting them would shed arrivals below the configured depth and
        inflate every depth diagnostic."""
        return sum(1 for w in self._waiters if not w.granted)

    def depth(self) -> int:
        with self.cv:
            return self._depth_locked()

    def _weight(self, tenant: str) -> float:
        w = self.weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def _select_locked(self) -> Optional[_Waiter]:
        """Best ungranted waiter: max priority, then min tenant pass, then
        arrival order."""
        best: Optional[_Waiter] = None
        for w in self._waiters:
            if w.granted:
                continue
            if best is None:
                best = w
                continue
            if w.priority != best.priority:
                if w.priority > best.priority:
                    best = w
                continue
            wp = self._tenant_pass.get(w.tenant, 0.0)
            bp = self._tenant_pass.get(best.tenant, 0.0)
            if wp != bp:
                if wp < bp:
                    best = w
                continue
            if w.seq < best.seq:
                best = w
        return best

    def _grant_locked(self) -> None:
        granted_any = False
        while self.holders < self.tokens:
            w = self._select_locked()
            if w is None:
                break
            w.granted = True
            self.holders += 1
            self.order += 1
            w.order = self.order
            self._tenant_pass[w.tenant] = (
                self._tenant_pass.get(w.tenant, 0.0)
                + _STRIDE / self._weight(w.tenant))
            granted_any = True
        if granted_any:
            self.cv.notify_all()

    def _remove_locked(self, w: _Waiter) -> None:
        if w in self._waiters:
            self._waiters.remove(w)
        if w.granted:  # granted but unconsumed: return the token
            self.holders -= 1
            w.granted = False
        self._grant_locked()

    # ------------------------------------------------------------------
    def acquire(self, priority: int = 0, tenant: str = "default",
                timeout: Optional[float] = None,
                token=None,
                alive: Optional[Callable[[], bool]] = None,
                apply_shed: bool = True):
        """Block until admitted.

        Returns the global admission order on grant, None on a plain
        `timeout` expiry (the service maps that to its admission-timeout
        reply), or ABANDONED when `alive` reported the caller gone.
        Raises QueryRejectedError (shed / injected fault),
        QueryCancelledError, or DeadlineExceededError (typed, query never
        admitted).

        `apply_shed=False` exempts this waiter from the depth/wait
        load-shedding limits: a context-less LAZY acquire (the historical
        mid-query path preserved in sched mode) must never be shed —
        QueryRejectedError promises the query never touched the device,
        and a mid-query acquire has already done scan/shuffle work."""
        try:
            faults.fire(faults.SCHED_ADMIT)
        except (QueryRejectedError, QueryCancelledError,
                DeadlineExceededError):
            raise
        except Exception as e:  # degrade, never crash the admission door
            with self.cv:
                self.shed_count += 1
            telemetry.count_rejection(tenant)
            raise QueryRejectedError(
                f"admission degraded by injected fault: "
                f"{type(e).__name__}: {e}",
                tenant=tenant, priority=priority) from e
        if token is not None:
            token.check()

        def wake() -> None:  # cancel() pokes parked waiters via this
            with self.cv:
                self.cv.notify_all()

        with self.cv:
            depth = self._depth_locked()
            if apply_shed and self.max_depth and depth >= self.max_depth:
                self.shed_count += 1
                telemetry.count_rejection(tenant)
                raise QueryRejectedError(
                    f"admission queue full: depth {depth} >= max "
                    f"{self.max_depth} "
                    f"(spark.rapids.tpu.sched.maxQueueDepth)",
                    depth=depth, tenant=tenant, priority=priority)
            self._seq += 1
            w = _Waiter(self._seq, priority, tenant)
            # a (re)joining tenant starts at the current floor, so idling
            # never banks fair-share credit. The floor is the min pass of
            # tenants with waiters CURRENTLY queued (the runnable set) —
            # an idle tenant's stale pass must not pin it, or that tenant
            # (and any newcomer) would rejoin with exactly the banked
            # credit this rule exists to deny. With nothing queued, the
            # MAX pass ever reached is the floor: a solo arrival competes
            # with no one, and the next contender starts level with it.
            if self._tenant_pass:
                queued = {ww.tenant for ww in self._waiters}
                pool = [p for t, p in self._tenant_pass.items()
                        if t in queued]
                floor = min(pool) if pool else \
                    max(self._tenant_pass.values())
                cur = self._tenant_pass.get(w.tenant)
                self._tenant_pass[w.tenant] = (
                    floor if cur is None else max(cur, floor))
            self._waiters.append(w)
            self.peak_depth = max(self.peak_depth, self._depth_locked())
            self._grant_locked()
        t0 = time.monotonic()
        try:
            if token is not None:
                token.add_waiter(wake)
                if token.cancelled:
                    # a cancel that completed BEFORE the registration
                    # will never fire wake(); observed here, or the
                    # un-clamped wait below could park forever
                    token.check()
            with self.cv:
                while not w.granted:
                    waited = time.monotonic() - t0
                    limits = []
                    if timeout is not None:
                        limits.append(timeout - waited)
                    if apply_shed and self.max_wait_s:
                        limits.append(self.max_wait_s - waited)
                    if token is not None:
                        rem = token.remaining_s()
                        if rem is not None:
                            limits.append(rem)
                    if limits and min(limits) <= 0:
                        if timeout is not None and waited >= timeout:
                            self._remove_locked(w)
                            return None
                        if token is not None and token.expired:
                            self._remove_locked(w)
                            telemetry.inc("tpu_sched_deadline_total",
                                          tenant=tenant)
                            telemetry.observe(
                                "tpu_sched_admission_wait_seconds",
                                waited, tenant=tenant)
                            raise DeadlineExceededError(
                                f"query deadline of {token.deadline_s}s "
                                f"expired after {waited:.3f}s in the "
                                f"admission queue",
                                deadline_s=token.deadline_s)
                        self.shed_count += 1
                        self._remove_locked(w)
                        telemetry.count_rejection(tenant)
                        raise QueryRejectedError(
                            f"admission queue wait {waited * 1e3:.0f}ms "
                            f"exceeded max "
                            f"{self.max_wait_s * 1e3:.0f}ms "
                            f"(spark.rapids.tpu.sched.maxQueueWaitMs)",
                            depth=self._depth_locked(), waited_s=waited,
                            tenant=tenant, priority=priority)
                    # tokens need no poll slice: cancel() wakes the cv
                    # through the registered waiter and the deadline
                    # remainder is already in `limits`; only the `alive`
                    # probe (no callback channel) needs polling
                    slice_s = min(limits) if limits else None
                    if alive is not None:
                        slice_s = (self.ALIVE_POLL_S if slice_s is None
                                   else min(slice_s, self.ALIVE_POLL_S))
                    self.cv.wait(slice_s)
                    if token is not None and \
                            (token.cancelled or token.expired):
                        self._remove_locked(w)
                        telemetry.inc(
                            "tpu_sched_cancelled_total" if token.cancelled
                            else "tpu_sched_deadline_total", tenant=tenant)
                        telemetry.observe(
                            "tpu_sched_admission_wait_seconds",
                            time.monotonic() - t0, tenant=tenant)
                        token.check()  # raises the matching typed error
                    if alive is not None and not alive():
                        self._remove_locked(w)
                        return ABANDONED
                self._waiters.remove(w)
                telemetry.inc("tpu_sched_admissions_total", tenant=tenant)
                telemetry.observe("tpu_sched_admission_wait_seconds",
                                  time.monotonic() - t0, tenant=tenant)
                return w.order
        except BaseException:
            with self.cv:
                # cover exits taken outside the cv block (token.check
                # raising after _remove_locked already ran is fine: the
                # remove is idempotent and the grant was returned there)
                self._remove_locked(w)
            raise
        finally:
            if token is not None:
                token.remove_waiter(wake)

    def release(self, count: int = 1) -> None:
        with self.cv:
            self.holders = max(0, self.holders - count)
            self._grant_locked()
            self.cv.notify_all()


class QueryScheduler:
    """Process-wide scheduler the in-process `TpuSemaphore` delegates to
    when `spark.rapids.tpu.sched.enabled=true`. Wraps one AdmissionQueue
    with the conf-derived policy and the observability wiring (queue-wait
    span + TaskMetrics counters)."""

    def __init__(self, permits: int, conf):
        # ONE reading of the policy keys: the signature tuple is both the
        # rebuild-detection identity and the source every field below is
        # unpacked from, so the two can never drift
        self._signature = self.signature_for(permits, conf)
        (self.permits, self.default_priority, self.default_tenant,
         weights, max_depth, max_wait_s) = self._signature
        self.queue = AdmissionQueue(
            permits, weights=dict(weights),
            max_depth=max_depth, max_wait_s=max_wait_s)

    @staticmethod
    def signature_for(permits: int, conf) -> tuple:
        """Policy identity as a pure function of (permits, conf) —
        TpuSemaphore.initialize compares it to decide whether to rebuild
        without constructing a throwaway scheduler."""
        wait_ms = conf.get("spark.rapids.tpu.sched.maxQueueWaitMs")
        return (permits,
                int(conf.get("spark.rapids.tpu.sched.priority")),
                conf.get("spark.rapids.tpu.sched.tenant") or "default",
                tuple(sorted(parse_tenant_map(
                    conf.get("spark.rapids.tpu.sched.tenant.weights"))
                    .items())),
                conf.get("spark.rapids.tpu.sched.maxQueueDepth"),
                wait_ms / 1000.0 if wait_ms else 0.0)

    def signature(self) -> tuple:
        """Policy identity — TpuSemaphore.initialize rebuilds on change."""
        return self._signature

    def admit(self) -> int:
        """Admit the current thread's query (context-aware); returns the
        admission order. Raises the typed shed/cancel/deadline errors."""
        from ..utils import spans
        from ..utils.metrics import TaskMetrics
        ctx = _ctx.current()
        priority = ctx.priority if ctx is not None else self.default_priority
        tenant = ctx.tenant if ctx is not None else self.default_tenant
        token = ctx.token if ctx is not None else None
        tm = TaskMetrics.get()
        depth = self.queue.depth()
        tm.sched_queue_depth = max(tm.sched_queue_depth, depth)
        t0 = time.monotonic_ns()
        try:
            with spans.span("sched:admit", kind=spans.KIND_SEMAPHORE,
                            tenant=tenant, priority=priority,
                            depth=depth):
                # shedding applies to SCHEDULED queries only (admitted
                # once, at query start); a context-less lazy acquire is
                # mid-query and must wait, not shed (see acquire())
                order = self.queue.acquire(priority=priority, tenant=tenant,
                                           token=token,
                                           apply_shed=ctx is not None)
        except QueryRejectedError:
            tm.sched_rejected += 1
            raise
        except QueryCancelledError:
            tm.sched_cancelled += 1
            raise
        except DeadlineExceededError:
            tm.sched_deadline_exceeded += 1
            raise
        finally:
            tm.sched_queue_wait_ns += time.monotonic_ns() - t0
        tm.sched_admissions += 1
        return order

    def release(self) -> None:
        self.queue.release()
