"""Result & fragment cache — transparent cross-query materialized reuse.

The reference engine reuses work within ONE query (`ReusedExchangeExec`
instance caching) or on explicit request (`df.cache()`); a multi-tenant
`TpuServer` re-executes an identical dashboard query from scratch for
every client. This package adds the serving-layer multiplier the Presto
acceleration work leans on: a transparent cache of materialized columnar
fragments keyed by a canonical plan fingerprint (fingerprint.py), with
caching seams at the engine's natural fragment boundaries —

  * whole-query results  — plugin.TpuSession._execute_rewritten: a hit
    answers from the host copy WITHOUT device admission (no semaphore
    token, no scheduler grant — the cache-hit fast path);
  * scan output          — io/scanbase.TpuFileScanExec;
  * shuffle-exchange out — exec/exchange.TpuShuffleExchangeExec;
  * broadcast payloads   — exec/broadcast.TpuBroadcastExchangeExec.

Correctness gates: nondeterministic subtrees never get a key
(fingerprint.py fail-closed), the `cache.fragment` fault point degrades
ANY cache failure to recompute (never a wrong result), mid-flight
eviction under a streaming hit re-produces and skips already-served
batches, and single-flight per fingerprint dedups concurrent identical
queries across tenants.

Off-path contract (mirrors faults/telemetry/sched): with
`spark.rapids.tpu.rescache.enabled=false` (default) every hook below is
one module-global bool check, no cache object exists, and zero threads
are spawned — scripts/rescache_matrix.sh gates it."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator, Optional

from .cache import Entry, ResultCache
from .fingerprint import RESULT_CONF_KEYS, Fingerprint, fingerprint

__all__ = ["configure", "shutdown", "is_enabled", "get", "stats",
           "invalidate", "begin_query", "QueryCacheHandle",
           "fragment_stream", "cached_blob", "fingerprint",
           "ResultCache", "RESULT_CONF_KEYS", "persist_tier"]

_ACTIVE = False
_mu = threading.Lock()
_cache: Optional[ResultCache] = None
# persistent whole-query tier (persist.py); None unless
# spark.rapids.tpu.rescache.persist.dir is configured
_persist = None
_warmup_thread: Optional[threading.Thread] = None

# fragment seams bound their single-flight wait: a mid-query seam must
# not park forever behind another query's producer (whole-query waits are
# unbounded — the wait IS the dedup win)
FRAGMENT_WAIT_S = 30.0


def is_enabled() -> bool:
    return _ACTIVE


def get() -> Optional[ResultCache]:
    return _cache


def persist_tier():
    """The live PersistentResultTier, or None (tests / cache_stats)."""
    return _persist


def configure(conf) -> None:
    """Enable per `spark.rapids.tpu.rescache.*` (no-op when the switch is
    off or the cache is already up). Called from
    TpuSession.initialize_device, like telemetry.configure. With
    `rescache.persist.dir` set, the persistent whole-query tier comes up
    too and (unless `persist.warmup.enabled` is off) a background thread
    reloads the previous incarnation's results into the memory cache —
    the crash-recovery warm path."""
    global _ACTIVE, _cache, _persist, _warmup_thread
    if not conf.get("spark.rapids.tpu.rescache.enabled"):
        return
    with _mu:
        if _ACTIVE:
            return
        _cache = ResultCache(
            max_bytes=conf.get("spark.rapids.tpu.rescache.maxBytes"),
            min_recompute_ms=conf.get(
                "spark.rapids.tpu.rescache.minRecomputeMs"))
        persist_dir = conf.get("spark.rapids.tpu.rescache.persist.dir")
        if persist_dir:
            from .persist import PersistentResultTier
            _persist = PersistentResultTier(
                persist_dir,
                conf.get("spark.rapids.tpu.rescache.persist.maxBytes"))
        _ACTIVE = True
        if _persist is not None and _persist.available() and conf.get(
                "spark.rapids.tpu.rescache.persist.warmup.enabled"):
            cache, tier = _cache, _persist
            _warmup_thread = threading.Thread(
                target=tier.warmup_into,
                args=(cache, lambda: _ACTIVE and _cache is cache),
                name="rescache-warmup", daemon=True)
            _warmup_thread.start()


def shutdown() -> None:
    """Tear the cache down (tests / process exit): close every entry,
    drop all state. Persisted entries stay on disk — surviving restart
    is their entire purpose."""
    global _ACTIVE, _cache, _persist, _warmup_thread
    with _mu:
        _ACTIVE = False
        cache, _cache = _cache, None
        _persist = None
        th, _warmup_thread = _warmup_thread, None
    if th is not None and th.is_alive():
        th.join(timeout=10.0)
    if cache is not None:
        cache.invalidate()


def stats() -> Optional[dict]:
    cache = _cache
    if cache is None:
        return None
    snap = cache.stats()
    p = _persist
    if p is not None:
        snap["persist"] = p.stats_dict()
    return snap


def invalidate() -> int:
    """Drop every entry — memory AND disk. The op exists for in-place
    data rewrites the file-identity fingerprints cannot see; leaving the
    persisted copies behind would resurrect exactly those stale results
    at the next restart."""
    p = _persist
    if p is not None:
        p.clear()
    cache = _cache
    return cache.invalidate() if cache is not None else 0


# ---------------------------------------------------------------- helpers
def _tenant() -> str:
    from ..sched import context as _qctx
    return _qctx.current_tenant() or "default"


def _count_degraded(where: str, **attrs) -> None:
    """The ONE degrade-to-recompute accounting sequence (task counter,
    cache lifetime counter, telemetry counter, flight event) — every
    degrade path must report identically or the scrape surface and
    cache_stats drift apart."""
    from .. import telemetry
    from ..utils.metrics import TaskMetrics
    TaskMetrics.get().rescache_degraded += 1
    cache = _cache
    if cache is not None:
        cache.degraded_count += 1
    telemetry.inc("tpu_rescache_degraded_total")
    telemetry.flight("cache", "degraded", where=where, **attrs)


def _fault_gate(where: str) -> bool:
    """Fire the cache.fragment fault point; True = proceed, False =
    degrade (skip the cache this time — recompute, never a wrong or
    missing result)."""
    from .. import faults
    try:
        faults.fire(faults.CACHE_FRAGMENT)
        return True
    except Exception as e:
        _count_degraded(where, error=f"{type(e).__name__}: {e}")
        return False


def _count_hit(seam: str) -> None:
    from .. import telemetry
    from ..utils.metrics import TaskMetrics
    TaskMetrics.get().rescache_hits += 1
    telemetry.inc("tpu_rescache_hits_total", seam=seam, tenant=_tenant())


def _count_miss(seam: str) -> None:
    from .. import telemetry
    from ..utils.metrics import TaskMetrics
    TaskMetrics.get().rescache_misses += 1
    telemetry.inc("tpu_rescache_misses_total", seam=seam, tenant=_tenant())


# ----------------------------------------------------------- query seam
class QueryCacheHandle:
    """Owner-side handle for the whole-query seam: plugin.py calls
    complete(table) on success or abort() on any unwind, so parked
    single-flight waiters are always released."""

    __slots__ = ("_key", "_validators", "_t0", "hit", "_done")

    def __init__(self, key: str, validators, hit=None):
        self._key = key
        self._validators = validators
        self._t0 = time.monotonic_ns()
        self.hit = hit  # pyarrow Table on a cache hit, else None
        self._done = hit is not None

    def complete(self, table) -> None:
        if self._done:
            return
        self._done = True
        cache = _cache
        if cache is None:
            return
        if not _fault_gate("query.store"):
            cache.abort(self._key)
            return
        try:
            nbytes = int(table.nbytes)
        except Exception:
            nbytes = 0
        recompute_ns = time.monotonic_ns() - self._t0
        stored = cache.complete(self._key, "query", "table", table, nbytes,
                                recompute_ns,
                                validators=self._validators)
        # persistent tier: only results the memory cache judged storable,
        # and only validator-free fingerprints — a validator means
        # process-local identity (in-memory table id()) that a fresh
        # process could alias to different data
        p = _persist
        if stored and p is not None and not self._validators:
            p.store(self._key, table, "query", recompute_ns)

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        cache = _cache
        if cache is not None:
            cache.abort(self._key)


def begin_query(plan, conf) -> Optional[QueryCacheHandle]:
    """Whole-query seam entry. Returns None when the cache is off or the
    plan is uncacheable; a handle with `.hit` set (serve it — no device
    admission happens); or an owner handle (execute, then complete/abort).
    Concurrent identical queries park here (single-flight) and come back
    as hits when the owner finishes."""
    if not _ACTIVE:
        return None
    if not conf.get("spark.rapids.tpu.rescache.query.enabled"):
        return None
    cache = _cache
    if cache is None:
        return None
    if not _fault_gate("query.lookup"):
        return None
    fp = fingerprint(plan, conf, extra="query|")
    if fp is None:
        return None
    from ..utils import spans
    status, entry = cache.begin(fp.digest, "query")
    if status == "hit":
        table = entry.value
        cache.unpin(entry)  # host tables are immutable; safe past unpin
        if table is None:
            # entry closed between begin() and here (concurrent
            # invalidate): recompute WITHOUT a handle — this path was
            # never made owner, so a complete() would pop someone
            # else's in-flight marker
            _count_degraded("query.hit_closed")
            return None
        _count_hit("query")
        with spans.span("rescache:query", kind=spans.KIND_CACHE, hit=1,
                        bytes=entry.nbytes):
            pass
        from .. import telemetry
        telemetry.flight("cache", "query_hit", bytes=entry.nbytes)
        return QueryCacheHandle(fp.digest, fp.validators, hit=table)
    _count_miss("query")
    with spans.span("rescache:query", kind=spans.KIND_CACHE, hit=0):
        pass
    if status != "owner":
        # bypass (unstorable fingerprint): compute WITHOUT a handle — a
        # complete() here would pop another owner's in-flight marker
        return None
    # persistent-tier fallthrough: a restarted worker whose background
    # warmup has not reached this digest yet (or runs warmup-off) still
    # answers previously-hot fingerprints from disk — no device admission,
    # no recompute. We ARE the single-flight owner here, so completing
    # the cache with the loaded table also releases any parked waiters.
    p = _persist
    if p is not None and not fp.validators:
        loaded = p.load(fp.digest)
        if loaded is not None:
            table, meta = loaded
            try:
                nbytes = int(meta.get("nbytes") or 0) or int(table.nbytes)
            except Exception:
                nbytes = 1
            cache.complete(fp.digest, "query", "table", table,
                           max(nbytes, 1),
                           int(meta.get("recompute_ns", 0)),
                           validators=())
            p.count_hit()
            from ..utils.metrics import TaskMetrics
            TaskMetrics.get().rescache_persist_hits += 1
            _count_hit("query")
            with spans.span("rescache:query", kind=spans.KIND_CACHE,
                            hit=1, persist=1):
                pass
            from .. import telemetry
            telemetry.flight("cache", "persist_hit",
                             bytes=int(meta.get("nbytes") or 0))
            return QueryCacheHandle(fp.digest, fp.validators, hit=table)
    return QueryCacheHandle(fp.digest, fp.validators)


# -------------------------------------------------------- fragment seams
def fragment_stream(node, seam: str,
                    produce: Callable[[], Iterator]) -> Iterator:
    """Wrap a fragment-producing exec seam (scan / exchange).

    Miss: stream `produce()` through, parking a spillable copy of every
    batch; the completed list becomes the cache entry. Hit: materialize
    the stored fragments back onto the device; any failure mid-stream
    (eviction, injected fault, spill-read error) degrades to a fresh
    `produce()` that skips the batches already served — in-process
    producers are deterministic, so batch boundaries repeat."""
    if not _ACTIVE:
        yield from produce()
        return
    conf = node.conf
    if not conf.get(f"spark.rapids.tpu.rescache.{seam}.enabled"):
        yield from produce()
        return
    if seam == "exchange" and conf.get("spark.rapids.shuffle.mode") == "ICI" \
            and not conf.get("spark.rapids.tpu.mesh.enabled"):
        # the dryrun-era ICI gate, kept verbatim for legacy mode. Under
        # the sharded-execution subsystem (mesh/) the seam is un-gated:
        # resident exchanges hand out per-device shard batches that park
        # as ordinary chip-tagged spillables, and non-resident outputs
        # (replicated slices of the sharded global) round-trip the
        # catalog's park->host->disk->unspill path exactly (verified in
        # test_mesh's replay test + the PR-15 review probe) — a repeated
        # subplan replays its mesh-exchanged partitions with positional
        # alignment preserved (empties are stored too).
        yield from produce()
        return
    cache = _cache
    if cache is None or not _fault_gate(f"{seam}.lookup"):
        yield from produce()
        return
    fp = fingerprint(node, conf, extra=f"{seam}|")
    if fp is None:
        yield from produce()
        return
    from ..utils import spans
    status, entry = cache.begin(fp.digest, seam,
                                max_wait_s=FRAGMENT_WAIT_S)
    if status == "hit":
        _count_hit(seam)
        with spans.span(f"rescache:{seam}", kind=spans.KIND_CACHE, hit=1,
                        bytes=entry.nbytes):
            pass
        try:
            yield from _serve_fragments(node, entry, produce)
        finally:
            cache.unpin(entry)
        return
    _count_miss(seam)
    with spans.span(f"rescache:{seam}", kind=spans.KIND_CACHE, hit=0):
        pass
    if status != "owner":  # bypass: compute without storing
        yield from produce()
        return
    yield from _produce_and_store(node, seam, fp, produce)


def _serve_fragments(node, entry: Entry, produce) -> Iterator:
    from ..errors import (DeadlineExceededError, QueryCancelledError,
                          QueryRejectedError)
    value = entry.value
    if value is None:
        # entry closed between begin() and here (invalidate/shutdown runs
        # regardless of pins): recompute from scratch — an empty tuple
        # here would silently serve ZERO batches as the "result"
        _count_degraded("fragment.hit_closed", seam=entry.seam)
        yield from produce()
        return
    frags = tuple(value)
    served = 0
    served_rows = 0
    try:
        for sb in frags:
            batch = sb.get_batch()
            rows = int(batch.row_count())
            node.num_output_rows.add(rows)
            served_rows += rows
            yield node._count_output(batch)
            served += 1
        return
    except (QueryCancelledError, DeadlineExceededError,
            QueryRejectedError):
        raise  # typed unwinds are the query's, not the cache's
    except GeneratorExit:
        raise
    except Exception as e:
        # mid-flight eviction / injected fault / spill-read failure:
        # degrade to recompute, skipping what already went downstream
        _count_degraded("fragment.hit_midflight", seam=entry.seam,
                        served=served, error=f"{type(e).__name__}: {e}")
    # the fresh produce() recounts EVERY batch it yields — including the
    # skipped prefix this stream already counted above — so pre-credit
    # the served prefix or the operator's output metrics double-count
    # exactly on the incident runs where accurate numbers matter
    node.num_output_rows.add(-served_rows)
    node.num_output_batches.add(-served)
    it = produce()
    skipped = 0
    for batch in it:
        if skipped < served:
            skipped += 1
            continue
        yield batch


def _produce_and_store(node, seam: str, fp: Fingerprint,
                       produce) -> Iterator:
    from ..memory.catalog import SpillPriority
    from ..memory.spillable import SpillableColumnarBatch
    from ..sched import context as _qctx
    cache = _cache
    frags = []
    total = 0
    t0 = time.monotonic_ns()
    try:
        for batch in produce():
            # park a handle on the SAME immutable device arrays (no copy)
            # under NO tenant context: a shared cache entry must not pin
            # one query's sub-quota ledger until eviction
            with _qctx.suspend():
                frags.append(SpillableColumnarBatch(
                    batch, priority=SpillPriority.BUFFERED))
            total += int(batch.device_memory_size())
            yield batch
    except BaseException:
        for sb in frags:
            try:
                sb.close()
            except Exception:
                pass
        if cache is not None:
            cache.abort(fp.digest)
        raise
    if cache is None or not _fault_gate(f"{seam}.store"):
        for sb in frags:
            sb.close()
        if cache is not None:
            cache.abort(fp.digest)
        return
    if not cache.complete(fp.digest, seam, "frags", frags, total,
                          time.monotonic_ns() - t0,
                          validators=fp.validators):
        for sb in frags:
            sb.close()


# -------------------------------------------------------- broadcast seam
def cached_blob(node, compute: Callable[[], Optional[bytes]]
                ) -> Optional[bytes]:
    """Broadcast-payload seam: returns the cached host blob, or runs
    `compute()` and stores its result. None (empty build side) is never
    cached — the exec's own `_empty` latch handles it."""
    if not _ACTIVE:
        return compute()
    conf = node.conf
    if not conf.get("spark.rapids.tpu.rescache.broadcast.enabled"):
        return compute()
    cache = _cache
    if cache is None or not _fault_gate("broadcast.lookup"):
        return compute()
    # the stored bytes are a serialized frame: codec + checksum framing
    # are part of the VALUE's format, so they join the key namespace
    codec = conf.get("spark.rapids.shuffle.compression.codec")
    crc = conf.get("spark.rapids.shuffle.checksum.enabled")
    fp = fingerprint(node, conf, extra=f"broadcast|{codec}|{crc}|")
    if fp is None:
        return compute()
    from ..utils import spans
    status, entry = cache.begin(fp.digest, "broadcast",
                                max_wait_s=FRAGMENT_WAIT_S)
    if status == "hit":
        blob = entry.value
        cache.unpin(entry)  # bytes are immutable; safe past unpin
        if blob is None:
            # entry closed under us (concurrent invalidate): degrade to
            # recompute like every other seam, never crash the query
            _count_degraded("broadcast.hit_closed")
            return compute()
        _count_hit("broadcast")
        with spans.span("rescache:broadcast", kind=spans.KIND_CACHE,
                        hit=1, bytes=len(blob)):
            pass
        return blob
    _count_miss("broadcast")
    with spans.span("rescache:broadcast", kind=spans.KIND_CACHE, hit=0):
        pass
    if status != "owner":
        return compute()
    t0 = time.monotonic_ns()
    try:
        blob = compute()
    except BaseException:
        cache.abort(fp.digest)
        raise
    if blob is None or not _fault_gate("broadcast.store"):
        cache.abort(fp.digest)
        return blob
    cache.complete(fp.digest, "broadcast", "blob", blob, len(blob),
                   time.monotonic_ns() - t0, validators=fp.validators)
    return blob
