"""Persistent whole-query result tier — crash recovery for the serving
fleet's warm state.

PR 10's gateway routes AROUND a dead worker; this tier is what makes the
respawned worker worth routing BACK to: whole-query results whose
fingerprints are validator-free (pure file/delta identity — no
process-local object ids) persist to `spark.rapids.tpu.rescache.persist.
dir` with the compile-cache discipline, and a restarted worker reloads
them on device init, answering previously-hot dashboard fingerprints in
milliseconds with ZERO device admissions instead of a ~7s cold
recompute.

Entry format (one `<digest>.qres` file per fingerprint):

    magic "SRQR1" | u8 version | u32 crc32c(body) | u32 meta_len | body
    body = meta JSON (seam, rows, nbytes, recompute_ns, ts)
         + Arrow IPC stream of the result table

A torn tail, a bit-flipped payload (CRC mismatch), or undecodable IPC is
a MISS + DELETE — never a wrong result (the same contract as the
compile cache's .xprog entries). Staleness needs no sidecar state: file
mtime/size and delta versions are INSIDE the fingerprint, so an entry
persisted against rewritten data is simply never looked up again
(`rescache.invalidate()` additionally wipes the directory — its whole
point is the in-place rewrite file identity cannot see, which a restart
would otherwise resurrect from disk).

IO failures degrade the tier through `utils/durable.py` (typed warning
+ `tpu_persist_degraded_total{tier="rescache"}` + one flight-recorder
incident) and queries keep computing; the `persist` fault point drives
that path, with `corrupt` rules poisoning loaded blobs."""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import List, Optional, Tuple

__all__ = ["PersistentResultTier"]

_MAGIC = b"SRQR1"
_HDR = struct.Struct("<5sBII")  # magic, version, crc32c(body), meta_len
_VERSION = 1
_SUFFIX = ".qres"


class PersistentResultTier:
    """Constructed only by rescache.configure() when
    `spark.rapids.tpu.rescache.persist.dir` is set."""

    def __init__(self, dir_path: str, max_bytes: int):
        self.dir = dir_path
        self.max_bytes = int(max_bytes)
        from ..utils import durable
        self.tier = durable.tier("rescache", dir_path)
        self._mu = threading.Lock()
        self.stores = 0
        self.hits = 0        # persisted entries served to a query
        self.warmed = 0      # entries preloaded into memory at startup
        self.poisoned = 0    # torn/corrupt entries deleted on load
        self.gc_evictions = 0
        self.tier.run("mkdir",
                      lambda: os.makedirs(dir_path, exist_ok=True))

    def available(self) -> bool:
        return self.tier.available()

    def _path(self, digest: str) -> str:
        return os.path.join(self.dir, digest + _SUFFIX)

    # ---------------------------------------------------------------- store
    def store(self, digest: str, table, seam: str,
              recompute_ns: int) -> bool:
        """Persist one result table (tmp-write + atomic rename). Returns
        True when the entry landed; any IO failure degrades the tier and
        returns False — the in-memory entry still serves this process."""
        if not self.available():
            return False
        from ..shuffle.codec import crc32c
        try:
            from ..service.protocol import table_to_ipc
            payload = table_to_ipc(table)
            meta = json.dumps({
                "seam": seam, "rows": int(table.num_rows),
                "nbytes": int(table.nbytes),
                "recompute_ns": int(recompute_ns),
                "ts": time.time()}).encode()
        except Exception:
            return False  # an unserializable ENTRY skips itself
        body = meta + payload
        blob = _HDR.pack(_MAGIC, _VERSION, crc32c(body), len(meta)) + body
        if len(blob) > self.max_bytes:
            return False  # one entry over the whole tier budget

        def write() -> bool:
            path = self._path(digest)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            return True

        if not self.tier.run("store", write):
            return False
        with self._mu:
            self.stores += 1
        from .. import telemetry
        telemetry.inc("tpu_rescache_persist_total", event="store")
        self._gc()
        return True

    # ----------------------------------------------------------------- load
    def load(self, digest: str) -> Optional[Tuple[object, dict]]:
        """(table, meta) for one persisted entry, or None. A torn or
        poisoned entry is deleted and treated as a miss — the recompute
        re-persists a good one."""
        if not self.available():
            return None
        path = self._path(digest)

        def read():
            with open(path, "rb") as f:
                return f.read()

        blob = self.tier.run("load", read, missing_ok=True,
                             corruptible=True)
        if blob is None:
            return None
        decoded = self._decode(blob)
        if decoded is None:
            with self._mu:
                self.poisoned += 1
            from .. import telemetry
            telemetry.inc("tpu_rescache_persist_total", event="poisoned")
            telemetry.flight("persist", "poisoned_entry", tier="rescache",
                             digest=digest)
            self.tier.run("delete", lambda: os.unlink(path),
                          missing_ok=True)
            return None
        return decoded

    @staticmethod
    def _decode(blob: bytes) -> Optional[Tuple[object, dict]]:
        try:
            if len(blob) < _HDR.size:
                return None
            magic, ver, crc, meta_len = _HDR.unpack_from(blob)
            if magic != _MAGIC or ver != _VERSION:
                return None
            body = blob[_HDR.size:]
            if len(body) < meta_len:
                return None
            from ..shuffle.codec import crc32c
            if crc32c(body) != crc:
                return None
            meta = json.loads(body[:meta_len].decode())
            from ..service.protocol import ipc_to_table
            table = ipc_to_table(body[meta_len:])
            if int(meta.get("rows", -1)) != int(table.num_rows):
                return None
            return table, meta
        except Exception:
            return None

    # ------------------------------------------------------------- lifecycle
    def entries(self) -> List[str]:
        if not self.available():
            return []
        return self.tier.run(
            "list", lambda: [f[:-len(_SUFFIX)]
                             for f in os.listdir(self.dir)
                             if f.endswith(_SUFFIX)], default=[])

    def clear(self) -> int:
        """Delete every persisted entry (the cache_invalidate hammer —
        an in-place data rewrite the fingerprint's file identity cannot
        see MUST not come back from disk on the next restart)."""
        digests = self.entries()

        def wipe() -> int:
            n = 0
            for d in digests:
                try:
                    os.unlink(self._path(d))
                    n += 1
                except FileNotFoundError:
                    pass
            return n

        return self.tier.run("clear", wipe, default=0) or 0

    def warmup_into(self, cache, is_active) -> int:
        """Background warmup (rescache.configure spawns the thread): pull
        every persisted entry into the in-memory cache so the first
        post-restart dashboard hit needs no disk read. `is_active` is
        polled per entry so shutdown() stops a half-done warmup cleanly.
        Live entries/in-flight owners always win over warmed copies."""
        n = 0
        for digest in self.entries():
            if not is_active():
                break
            loaded = self.load(digest)
            if loaded is None:
                continue
            table, meta = loaded
            if cache.adopt(digest, meta.get("seam", "query"), "table",
                           table, int(meta.get("nbytes") or table.nbytes),
                           int(meta.get("recompute_ns", 0))):
                n += 1
        with self._mu:
            self.warmed += n
        if n:
            from .. import telemetry
            telemetry.inc("tpu_rescache_persist_total", value=n,
                          event="warmed")
            telemetry.flight("persist", "warmup_done", tier="rescache",
                             entries=n)
        return n

    def count_hit(self) -> None:
        with self._mu:
            self.hits += 1
        from .. import telemetry
        telemetry.inc("tpu_rescache_persist_total", event="hit")

    # ----------------------------------------------------------------- gc
    def _gc(self) -> None:
        """Bound the directory at max_bytes: oldest entries (mtime) leave
        first. Runs after each store; store traffic is per DISTINCT query,
        so the listdir stays off any hot path."""
        def collect():
            out = []
            for f in os.listdir(self.dir):
                if not f.endswith(_SUFFIX):
                    continue
                p = os.path.join(self.dir, f)
                try:
                    st = os.stat(p)
                except FileNotFoundError:
                    continue
                out.append((st.st_mtime_ns, st.st_size, p))
            return out

        files = self.tier.run("gc", collect, default=[])
        if not files:
            return
        total = sum(sz for _, sz, _ in files)
        if total <= self.max_bytes:
            return
        files.sort()
        for _, sz, p in files:
            if total <= self.max_bytes:
                break
            self.tier.run("gc", lambda p=p: os.unlink(p), missing_ok=True)
            total -= sz
            with self._mu:
                self.gc_evictions += 1

    # -------------------------------------------------------------- stats
    def stats_dict(self) -> dict:
        with self._mu:
            return {"dir": self.dir, "available": self.available(),
                    "degraded": self.tier.degraded,
                    "stores": self.stores, "hits": self.hits,
                    "warmed": self.warmed, "poisoned": self.poisoned,
                    "gc_evictions": self.gc_evictions,
                    "entries": len(self.entries())}
