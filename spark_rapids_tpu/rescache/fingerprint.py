"""Canonical plan fingerprints — the result/fragment cache's key discipline.

A fingerprint is a sha256 over a CANONICAL STRING of the (sub)plan: node
class names, each node's param-faithful argument rendering (`_arg_string`
plus every expression/primitive attribute — the same repr discipline the
compile service's program keys ride, so an alias here is a wrong-results
bug twice over), output schema, source identity (per-file
`(path, mtime_ns, size)`, in-memory table object identity, or an explicit
`fingerprint_token` such as a delta `(path, version)`), and the conf keys
that change results.

Fail-closed contract: anything this module cannot PROVE it renders
faithfully yields no key (None = uncacheable), never a lossy key —

  * node classes outside the explicit whitelist (UDF execs hold opaque
    python callables; a future exec is uncacheable until audited here);
  * any expression with `deterministic=False` (rand/uuid/current-time
    style, pandas UDFs, partition-id family) anywhere in the subtree;
  * attribute values of types this module does not know how to render
    (over-inclusion only lowers the hit rate; silent omission would
    serve query A's bytes to query B);
  * scans carrying runtime dynamic-pruning filters (their output depends
    on a join's build keys, which are not part of the plan).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import weakref
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["Fingerprint", "fingerprint", "RESULT_CONF_KEYS"]


# conf keys whose value changes RESULTS (not just placement/performance):
# two queries differing in any of these must never share a cache entry.
RESULT_CONF_KEYS = (
    "spark.rapids.sql.enabled",
    "spark.rapids.sql.mode",
    "spark.rapids.sql.ansi.enabled",
    "spark.sql.ansi.enabled",
    "spark.rapids.sql.variableFloatAgg.enabled",
    "spark.rapids.sql.improvedFloatOps.enabled",
    "spark.rapids.sql.hasNans",
    "spark.rapids.sql.incompatibleOps.enabled",
    "spark.rapids.sql.incompatibleDateFormats.enabled",
    "spark.rapids.sql.regexp.enabled",
    "spark.rapids.sql.castFloatToString.enabled",
    "spark.rapids.sql.castStringToFloat.enabled",
    "spark.rapids.tpu.f64.emulation",
    "spark.rapids.tpu.string.maxWidth",
    "spark.rapids.tpu.string.headWidth",
    "spark.rapids.shuffle.mode",
)

# explicitly-set per-op/per-expression enable keys also move subtrees
# between engines (ULP-level result differences for incompat ops), so any
# set key under these prefixes joins the conf section of the fingerprint
_CONF_PREFIXES = ("spark.rapids.sql.expression.", "spark.rapids.sql.exec.",
                  "spark.rapids.sql.format.")


@dataclasses.dataclass
class Fingerprint:
    """digest: the cache key. validators: zero-arg callables that must ALL
    return True at hit time (weakref identity checks for in-memory
    sources — a freed table's id() could be reassigned to different
    data, so id-in-the-key alone is not enough)."""
    digest: str
    validators: Tuple[Callable[[], bool], ...] = ()

    def valid(self) -> bool:
        try:
            return all(v() for v in self.validators)
        except Exception:
            return False


class _Uncacheable(Exception):
    """Internal control flow: some part of the subtree cannot be rendered
    faithfully (carries the reason for diagnostics)."""


# ---------------------------------------------------------------------------
# node whitelist: every class named here has been audited — its
# `_arg_string` + public attributes render its full result-relevant
# identity. Names, not classes, to avoid import cycles at module load.
_PLAN_NODES = frozenset({
    # plan/nodes.py (CPU plan)
    "CpuScanExec", "CpuProjectExec", "CpuFilterExec", "CpuHashAggregateExec",
    "CpuGenerateExec", "CpuHashJoinExec", "CpuSortExec", "CpuSampleExec",
    "CpuLimitExec", "CpuUnionExec", "CpuRangeExec", "CpuExpandExec",
    "CpuWindowExec", "CpuShuffleExchangeExec",
    # io/ format scans (CpuFileScanExec subclasses get the source-identity
    # handler below)
    "CpuParquetScanExec", "CpuCsvScanExec", "CpuJsonScanExec",
    "CpuOrcScanExec", "CpuAvroScanExec", "CpuHiveTextScanExec",
    # datasources/cache.py — output identical to the child's
    "CpuCachedExec",
    # exec/ (TPU operators; fragment seams fingerprint these subtrees)
    "TpuScanExec", "TpuProjectExec", "TpuFilterExec", "TpuHashAggregateExec",
    "TpuGenerateExec", "TpuSortExec", "TpuTopKExec", "TpuSampleExec",
    "TpuLimitExec", "TpuUnionExec", "TpuRangeExec", "TpuExpandExec",
    "TpuWindowExec", "TpuCoalesceBatchesExec", "TpuShuffleExchangeExec",
    "TpuBroadcastExchangeExec", "TpuBroadcastHashJoinExec",
    "TpuShuffledHashJoinExec", "TpuNestedLoopJoinExec", "TpuFileScanExec",
    "TpuInMemoryTableScanExec", "TpuFromCpuExec",
    # mesh/shard.py — a sharding wrapper: output identical to the wrapped
    # scan's (the shard layout moves rows between chips, never changes
    # them), so its identity is its child subtree
    "MeshShardedScanExec",
    # exec/fused.py — identity is the audited FusedStageSpec repr (public
    # `spec`) plus member_exprs (rendered AND determinism-checked, so a
    # rand()/UDF member fails closed exactly like its unfused form) plus
    # the source/build children
    "TpuFusedStageExec",
})

# attribute names that are runtime machinery, never result identity
_IGNORED_ATTRS = frozenset({
    "children", "conf", "metrics", "session", "cpu_scan", "cpu_node",
    "cpu_plan", "tpu_exec", "table", "relation", "lock",
    "dynamic_filters", "dpp_filters", "fingerprint_token",
    "paths", "options", "columns",
})

# attr value types that are runtime machinery (rendered as nothing)
_IGNORED_TYPE_NAMES = frozenset({
    "Metric", "MetricsSet", "TpuConf", "lock", "RLock", "Event",
    "Condition", "DynamicKeyFilter",
})


def _render(value: Any, out: List[str]) -> None:
    """Render one attribute value into the canonical string, or raise
    _Uncacheable for anything not provably faithful."""
    from ..expr.base import Expression
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        out.append(repr(value))
        return
    if isinstance(value, Expression):
        out.append(repr(value))
        return
    if isinstance(value, (list, tuple)):
        out.append("[")
        for v in value:
            _render(v, out)
            out.append(",")
        out.append("]")
        return
    if isinstance(value, dict):
        out.append("{")
        for k in sorted(value, key=repr):
            out.append(repr(k))
            out.append(":")
            _render(value[k], out)
            out.append(",")
        out.append("}")
        return
    tname = type(value).__name__
    if tname in _IGNORED_TYPE_NAMES:
        return
    if callable(value) and not isinstance(value, type):
        raise _Uncacheable(f"opaque callable of type {tname}")
    # schema / dtype objects render via simple_string (stable, canonical)
    if hasattr(value, "simple_string"):
        out.append(value.simple_string())
        return
    # Schema (columnar/batch.py): names + types
    if hasattr(value, "names") and hasattr(value, "types"):
        out.append(repr(tuple(value.names)))
        for t in value.types:
            out.append(t.simple_string())
        return
    if dataclasses.is_dataclass(value):
        # partition specs, AggExpr, window frames: dataclass/custom reprs
        # are param-faithful by construction
        out.append(repr(value))
        return
    # windowexprs frames and similar small param carriers define __repr__
    if type(value).__repr__ is not object.__repr__:
        out.append(repr(value))
        return
    # plain param-carrier objects (e.g. coalesce TargetSize): class name +
    # every attribute, recursively — fails closed on anything nested that
    # this renderer does not understand
    d = getattr(value, "__dict__", None)
    if d is not None:
        out.append(tname)
        out.append("{")
        for k in sorted(d):
            out.append(k)
            out.append("=")
            _render(d[k], out)
            out.append(",")
        out.append("}")
        return
    raise _Uncacheable(f"unrenderable attr value of type {tname}")


# expression classes that wrap an opaque user callable: their __repr__
# cannot render the function body, so two different UDFs registered under
# the same name would alias — fail closed even when the SPI marks them
# deterministic (PandasUDF is deterministic=False already; ColumnarUDFExpr
# defaults to deterministic=True)
_OPAQUE_EXPRS = frozenset({"ColumnarUDFExpr", "PandasUDF"})


def _check_deterministic(node: Any) -> None:
    """Any Expression reachable from this node's attributes must be
    deterministic AND repr-renderable — rand/uuid/current-time style
    expressions and UDF black boxes poison the whole subtree."""
    from ..expr.base import Expression

    def walk_value(v):
        if isinstance(v, Expression):
            if v.collect(lambda e: not e.deterministic
                         or type(e).__name__ in _OPAQUE_EXPRS):
                raise _Uncacheable(
                    f"nondeterministic or opaque-callable expression in "
                    f"{type(node).__name__}")
            return
        if isinstance(v, (list, tuple)):
            for x in v:
                walk_value(x)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for f in dataclasses.fields(v):
                walk_value(getattr(v, f.name))

    for name, v in vars(node).items():
        if name == "children":
            continue
        walk_value(v)


def _file_identity(paths) -> List[Tuple[str, int, int]]:
    """(path, mtime_ns, size) per source file — a rewritten file (content
    or timestamp) changes the key, so stale entries become unreachable
    and the query recomputes."""
    out = []
    for p in paths:
        st = os.stat(p)  # OSError -> caught by fingerprint() = uncacheable
        out.append((str(p), st.st_mtime_ns, st.st_size))
    return out


def _node_string(node: Any, out: List[str],
                 validators: List[Callable[[], bool]]) -> None:
    name = type(node).__name__
    if name == "CpuFromTpuExec":
        # host bridge: identity is the wrapped device subtree
        out.append("CpuFromTpuExec(")
        _node_string(node.tpu_exec, out, validators)
        out.append(")")
        return
    if name not in _PLAN_NODES:
        raise _Uncacheable(f"node class {name} is not fingerprint-audited")
    _check_deterministic(node)
    out.append(name)
    out.append("(")

    # ---- source identity ------------------------------------------------
    scan = getattr(node, "cpu_scan", None)  # TpuFileScanExec wraps one
    if scan is None and hasattr(node, "paths") and hasattr(node,
                                                           "decode_file"):
        scan = node  # a CpuFileScanExec itself
    if scan is not None:
        if getattr(node, "dynamic_filters", None):
            raise _Uncacheable(
                "scan output depends on runtime dynamic-pruning filters")
        out.append(getattr(scan, "format_name", "file"))
        _render(_file_identity(scan.paths), out)
        _render(scan.columns, out)
        _render(scan.options, out)
    table = getattr(node, "table", None)
    if table is not None and hasattr(table, "num_rows"):
        token = getattr(node, "fingerprint_token", None)
        if token is not None:
            # explicit stable identity (e.g. delta (path, version)): the
            # datasource re-reads the same versioned content for it
            _render(tuple(token), out)
        else:
            # in-memory table: object identity IS the identity (pyarrow
            # tables are immutable), valid only while that very object is
            # alive — the weakref validator turns a freed/reused id into
            # a miss instead of a wrong hit
            out.append(f"table@{id(table)}")
            ref = weakref.ref(table)
            validators.append(
                lambda ref=ref, tid=id(table):
                (lambda t: t is not None and id(t) == tid)(ref()))
    cached = getattr(node, "cpu_node", None)  # TpuInMemoryTableScanExec
    if cached is not None:
        _node_string(cached.children[0], out, validators)
    cpu_plan = getattr(node, "cpu_plan", None)  # TpuFromCpuExec bridge
    if cpu_plan is not None:
        _node_string(cpu_plan, out, validators)

    # ---- param-faithful argument rendering ------------------------------
    out.append(node._arg_string())
    for attr in sorted(vars(node)):
        if attr.startswith("_") or attr in _IGNORED_ATTRS:
            continue
        v = vars(node)[attr]
        if callable(v) and not isinstance(v, type):
            raise _Uncacheable(f"{name}.{attr} is an opaque callable")
        out.append(attr)
        out.append("=")
        _render(v, out)
        out.append(";")

    # ---- output schema + children ---------------------------------------
    try:
        _render(node.output, out)
    except Exception as e:
        raise _Uncacheable(f"{name}.output unavailable: {e}")
    for c in node.children:
        _node_string(c, out, validators)
    out.append(")")


def _conf_string(conf, out: List[str]) -> None:
    for k in RESULT_CONF_KEYS:
        out.append(f"{k}={conf.get(k)!r};")
    settings = getattr(conf, "_settings", {})
    for k in sorted(settings):
        if k.startswith(_CONF_PREFIXES):
            out.append(f"{k}={settings[k]!r};")


def fingerprint(node: Any, conf, extra: str = "") -> Optional[Fingerprint]:
    """Fingerprint of the subplan rooted at `node` (a CPU PhysicalPlan or
    a TPU exec), or None when any part of it is uncacheable. `extra`
    distinguishes seam namespaces (a whole-query entry and a fragment
    entry over the same subtree hold different value kinds)."""
    out: List[str] = [extra, "|v1|"]
    validators: List[Callable[[], bool]] = []
    try:
        _node_string(node, out, validators)
        _conf_string(conf, out)
    except (_Uncacheable, OSError, ValueError, AttributeError):
        return None
    digest = hashlib.sha256("".join(out).encode(
        "utf-8", "backslashreplace")).hexdigest()
    return Fingerprint(digest, tuple(validators))
