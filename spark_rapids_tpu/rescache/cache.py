"""ResultCache — the process-wide store behind the result/fragment cache.

Three entry kinds share one capacity and one eviction policy:

  * ``table`` — a whole-query result (host pyarrow Table; a hit serves
    straight from host memory, no device work);
  * ``blob``  — a broadcast payload (the host-serialized build side);
  * ``frags`` — a list of device fragments held as budget-visible
    ``SpillableColumnarBatch``es in the spill catalog, so cached data
    rides the device->host->disk tiers and is evicted from HBM under
    memory pressure exactly like any parked batch — the cache can never
    cause an OOM the engine could not already spill its way out of.

Eviction is cost-aware LRU: when an insert pushes the cache past
``spark.rapids.tpu.rescache.maxBytes`` the entry with the lowest
``recompute_seconds x (1 + hits) / bytes`` score leaves first (cheap
bulk before expensive small results), age as the tiebreak.

Single-flight: the first query to miss a fingerprint becomes the OWNER
and computes; concurrent identical queries (any tenant) park on the
in-flight marker and are served the stored entry when the owner
completes — N identical dashboard queries cost ONE execution. An owner
that fails aborts the marker so a waiter takes over (no livelock on a
poisoned key).

Thread-safety: one lock guards the maps; fragment materialization and
entry close run outside it (device transfers must not serialize the
whole cache)."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ResultCache", "Entry"]


class Entry:
    __slots__ = ("key", "kind", "seam", "value", "nbytes", "recompute_ns",
                 "hits", "created", "last_used", "validators", "pins",
                 "closed")

    def __init__(self, key: str, kind: str, seam: str, value: Any,
                 nbytes: int, recompute_ns: int, validators=()):
        self.key = key
        self.kind = kind          # "table" | "blob" | "frags"
        self.seam = seam          # "query" | "scan" | "exchange" | "broadcast"
        self.value = value
        self.nbytes = int(nbytes)
        self.recompute_ns = int(recompute_ns)
        self.hits = 0
        self.created = time.monotonic()
        self.last_used = self.created
        self.validators = tuple(validators)
        self.pins = 0             # hits currently streaming from this entry
        self.closed = False

    def score(self) -> float:
        """Eviction priority: higher = keep. Recompute seconds saved per
        byte held, amplified by observed reuse."""
        return (self.recompute_ns / 1e9) * (1 + self.hits) \
            / max(self.nbytes, 1)

    def close(self) -> None:
        """Release owned storage. Fragments are catalog handles that must
        be closed exactly once; host tables/blobs just drop."""
        if self.closed:
            return
        self.closed = True
        if self.kind == "frags":
            for sb in self.value:
                try:
                    sb.close()
                except Exception:
                    pass
        self.value = None


class _InFlight:
    __slots__ = ("cv", "done", "failed")

    def __init__(self):
        self.cv = threading.Condition()
        self.done = False
        self.failed = False


class ResultCache:
    """See module docstring. Constructed only by rescache.configure()."""

    # single-flight waiters poll in slices so cooperative cancellation
    # (sched CancelToken) can unwind a parked waiter with its typed error
    WAIT_SLICE_S = 0.05

    # fingerprints whose results proved unstorable (empty, over-capacity,
    # below the recompute floor) latch here so later identical queries run
    # CONCURRENTLY instead of serializing behind a single-flight owner
    # whose store will never land; bounded, and cleared on invalidate
    UNSTORABLE_CAP = 4096

    def __init__(self, max_bytes: int, min_recompute_ms: float = 0.0):
        self.max_bytes = int(max_bytes)
        self.min_recompute_ns = int(min_recompute_ms * 1e6)
        self._mu = threading.Lock()
        self._entries: Dict[str, Entry] = {}
        self._inflight: Dict[str, _InFlight] = {}
        self._unstorable: set = set()
        # lifetime stats (cache_stats service op / telemetry gauges)
        self.hit_count: Dict[str, int] = {}
        self.miss_count: Dict[str, int] = {}
        self.store_count: Dict[str, int] = {}
        self.eviction_count = 0
        self.invalidation_count = 0
        self.singleflight_waits = 0
        self.degraded_count = 0

    # ------------------------------------------------------------- lookup
    def begin(self, key: str, seam: str,
              max_wait_s: Optional[float] = None
              ) -> Tuple[str, Optional[Entry]]:
        """Returns ("hit", entry) with the entry PINNED (caller must
        unpin()), ("owner", None) — the caller computes and must call
        complete() or abort() — or ("bypass", None): compute without
        storing (this fingerprint's results proved unstorable, or the
        caller waited past max_wait_s for an owner that has not
        finished; a mid-query fragment seam must not park forever
        behind another query)."""
        from ..sched import context as _qctx
        from ..utils.metrics import TaskMetrics
        waited_ns = 0
        while True:
            stale: Optional[Entry] = None
            with self._mu:
                e = self._entries.get(key)
                if e is not None:
                    if self._valid_locked(e):
                        e.hits += 1
                        e.last_used = time.monotonic()
                        e.pins += 1
                        self.hit_count[seam] = \
                            self.hit_count.get(seam, 0) + 1
                        if waited_ns:
                            TaskMetrics.get() \
                                .rescache_singleflight_wait_ns += waited_ns
                        return "hit", e
                    # stale (source table freed): drop, close OUTSIDE
                    # the lock, and re-examine
                    stale = self._entries.pop(key, None)
            if stale is not None:
                stale.close()
                continue
            with self._mu:
                if key in self._unstorable:
                    # this result can never land (empty / over capacity /
                    # below the recompute floor): run concurrently, never
                    # serialize a burst behind an owner whose store is
                    # known to be declined
                    self.miss_count[seam] = self.miss_count.get(seam, 0) + 1
                    if waited_ns:
                        TaskMetrics.get() \
                            .rescache_singleflight_wait_ns += waited_ns
                    return "bypass", None
                fl = self._inflight.get(key)
                if fl is None:
                    self._inflight[key] = _InFlight()
                    self.miss_count[seam] = self.miss_count.get(seam, 0) + 1
                    if waited_ns:
                        TaskMetrics.get() \
                            .rescache_singleflight_wait_ns += waited_ns
                    return "owner", None
                if waited_ns == 0:
                    self.singleflight_waits += 1
                    from .. import telemetry
                    telemetry.inc("tpu_rescache_singleflight_waits_total",
                                  tenant=_qctx.current_tenant() or "default")
            t0 = time.monotonic_ns()
            with fl.cv:
                if not fl.done:
                    fl.cv.wait(self.WAIT_SLICE_S)
            waited_ns += time.monotonic_ns() - t0
            _qctx.checkpoint()  # typed cancel/deadline unwind while parked
            if max_wait_s is not None and waited_ns / 1e9 >= max_wait_s:
                with self._mu:
                    self.miss_count[seam] = self.miss_count.get(seam, 0) + 1
                TaskMetrics.get().rescache_singleflight_wait_ns += waited_ns
                return "bypass", None

    def unpin(self, entry: Entry) -> None:
        with self._mu:
            entry.pins = max(0, entry.pins - 1)

    def _valid_locked(self, e: Entry) -> bool:
        if e.closed:
            return False
        try:
            return all(v() for v in e.validators)
        except Exception:
            return False

    # -------------------------------------------------------------- store
    def complete(self, key: str, seam: str, kind: str, value: Any,
                 nbytes: int, recompute_ns: int, validators=()) -> bool:
        """Owner path: publish the computed entry and wake waiters.
        Returns False when the entry was not stored (below the
        min-recompute floor or zero-capacity) — waiters then recompute
        for themselves."""
        stored = False
        to_close: List[Entry] = []
        with self._mu:
            keep = (recompute_ns >= self.min_recompute_ns
                    and 0 < nbytes <= self.max_bytes)
            if keep:
                old = self._entries.pop(key, None)
                if old is not None:
                    to_close.append(old)
                e = Entry(key, kind, seam, value, nbytes, recompute_ns,
                          validators)
                self._entries[key] = e
                self.store_count[seam] = self.store_count.get(seam, 0) + 1
                to_close.extend(self._evict_over_capacity_locked())
                stored = key in self._entries
            else:
                # INHERENTLY unstorable (not capacity churn — an entry
                # evicted after insert may well land next time): latch so
                # concurrent identical queries stop single-flighting
                if len(self._unstorable) >= self.UNSTORABLE_CAP:
                    self._unstorable.clear()
                self._unstorable.add(key)
            fl = self._inflight.pop(key, None)
        if fl is not None:
            with fl.cv:
                fl.done = True
                fl.failed = not stored
                fl.cv.notify_all()
        for e in to_close:
            e.close()
        if stored:
            from ..utils.metrics import TaskMetrics
            TaskMetrics.get().rescache_stores += 1
        return stored

    def adopt(self, key: str, seam: str, kind: str, value: Any,
              nbytes: int, recompute_ns: int, validators=()) -> bool:
        """Warmup path (rescache/persist.py): insert an entry reloaded
        from the persistent tier, but only when the key is ABSENT — a
        live entry or an in-flight owner is fresher than a disk copy and
        must win. No unstorable latching, no waiter bookkeeping."""
        to_close: List[Entry] = []
        with self._mu:
            if key in self._entries or key in self._inflight:
                return False
            if not (0 < nbytes <= self.max_bytes):
                return False
            e = Entry(key, kind, seam, value, nbytes, recompute_ns,
                      validators)
            self._entries[key] = e
            to_close.extend(self._evict_over_capacity_locked())
            stored = key in self._entries
        for old in to_close:
            old.close()
        return stored

    def abort(self, key: str) -> None:
        """Owner path on failure: release the in-flight marker so a parked
        waiter can take over as the next owner."""
        with self._mu:
            fl = self._inflight.pop(key, None)
        if fl is not None:
            with fl.cv:
                fl.done = True
                fl.failed = True
                fl.cv.notify_all()

    # ----------------------------------------------------------- eviction
    def _evict_over_capacity_locked(self) -> List[Entry]:
        """Pop lowest-score entries until under max_bytes; pinned entries
        (a hit currently streaming from them) are skipped this round.
        Returns the popped entries for the caller to close OUTSIDE the
        lock."""
        out: List[Entry] = []
        total = sum(e.nbytes for e in self._entries.values())
        while total > self.max_bytes:
            victims = [e for e in self._entries.values() if e.pins == 0]
            if not victims:
                break
            v = min(victims, key=lambda e: (e.score(), e.last_used))
            self._entries.pop(v.key, None)
            total -= v.nbytes
            self.eviction_count += 1
            from .. import telemetry
            telemetry.inc("tpu_rescache_evictions_total", reason="capacity")
            out.append(v)
        return out

    # ------------------------------------------------------- invalidation
    def invalidate(self) -> int:
        """Drop every entry (service cache_invalidate op / tests); queries
        currently streaming a pinned entry keep their reference — the
        degrade-to-recompute path covers any fragment closed under them."""
        with self._mu:
            entries = list(self._entries.values())
            self._entries.clear()
            self._unstorable.clear()
            self.invalidation_count += 1
            from .. import telemetry
            for _ in entries:
                telemetry.inc("tpu_rescache_evictions_total",
                              reason="invalidate")
        for e in entries:
            e.close()
        return len(entries)

    # ----------------------------------------------------------- stats
    def total_bytes(self, kinds: Optional[Tuple[str, ...]] = None) -> int:
        with self._mu:
            return sum(e.nbytes for e in self._entries.values()
                       if kinds is None or e.kind in kinds)

    def bytes_by_kind(self) -> Dict[str, int]:
        """One locked pass for the telemetry gauge (a scrape must not
        take the hot-path lock three times per sample)."""
        out = {"frags": 0, "table": 0, "blob": 0}
        with self._mu:
            for e in self._entries.values():
                out[e.kind] = out.get(e.kind, 0) + e.nbytes
        return out

    @property
    def entry_count(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        with self._mu:
            per_seam = {}
            for e in self._entries.values():
                s = per_seam.setdefault(e.seam,
                                        {"entries": 0, "bytes": 0,
                                         "hits": 0})
                s["entries"] += 1
                s["bytes"] += e.nbytes
                s["hits"] += e.hits
            return {
                "entries": len(self._entries),
                "bytes": sum(e.nbytes for e in self._entries.values()),
                "max_bytes": self.max_bytes,
                "hits": dict(self.hit_count),
                "misses": dict(self.miss_count),
                "stores": dict(self.store_count),
                "evictions": self.eviction_count,
                "invalidations": self.invalidation_count,
                "unstorable": len(self._unstorable),
                "singleflight_waits": self.singleflight_waits,
                "degraded": self.degraded_count,
                "per_seam": per_seam,
            }
