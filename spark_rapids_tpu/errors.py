"""Framework exception types.

RetryOOM / SplitAndRetryOOM mirror the reference's per-thread retry exceptions raised by
RmmSpark (`RmmRapidsRetryIterator.scala:28-120` handles them); here they are raised by the
host-side budget tracker pre-flight instead of the allocator callback (ARCHITECTURE.md #6).
"""

from __future__ import annotations


class RapidsTpuError(Exception):
    """Base class for framework errors."""


class RetryOOM(RapidsTpuError):
    """Device memory pressure: block, spill, and retry the idempotent step."""


class SplitAndRetryOOM(RapidsTpuError):
    """Device memory pressure too high for retry alone: split the input and retry."""


class PlanNotFullyOnDevice(RapidsTpuError):
    """A zero-copy device handoff was requested but the plan has CPU
    sections; callers may fall back to host execution. Deliberately NOT a
    RuntimeError subclass so genuine runtime failures (XlaRuntimeError IS
    a RuntimeError) can never masquerade as this signal."""


class CpuFallbackRequired(RapidsTpuError):
    """A batch/op cannot execute on device; the planner/exec must take the host path."""


class StringWidthExceeded(CpuFallbackRequired):
    """A string batch exceeds spark.rapids.tpu.string.maxWidth for the fixed-width
    byte-matrix device layout; process this batch on host."""

    def __init__(self, width: int, limit: int):
        super().__init__(
            f"string batch max byte length {width} exceeds device layout limit "
            f"{limit} (spark.rapids.tpu.string.maxWidth)")
        self.width = width
        self.limit = limit


class DeviceStartupError(RapidsTpuError):
    """The device backend failed or HUNG during first touch (client init /
    device enumeration). Fatal for device execution: raised with diagnostics
    within the configured deadline instead of blocking the query forever —
    the analog of the reference's executor-startup inspection + fail-fast
    (`Plugin.scala:436-459`). The session can still run CPU-engine plans."""

    def __init__(self, message: str, diagnostics: dict | None = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


class AnsiViolation(RapidsTpuError):
    """Spark ANSI-mode runtime error (ArithmeticException analog): integral
    overflow, division by zero, or cast overflow under spark.sql.ansi.enabled."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class InjectedFault(RapidsTpuError, IOError):
    """Raised by the fault-injection subsystem (faults.py) when a rule fires
    without a more specific exception type configured. Also an IOError so
    injection points on I/O seams are caught by existing handlers."""


class CompileServiceWarning(RuntimeWarning):
    """The compile service degraded — a compile failed/was injected to
    fail, a persisted entry was poisoned, or a cached executable rejected a
    call — and the affected kernel fell back to a direct `jax.jit`. The
    query's RESULT is unaffected (the direct path traces the identical
    function); only caching/latency is."""


class PersistenceDegradedWarning(RuntimeWarning):
    """A durable tier (compile cache, statistics history, event log,
    persistent result tier) hit an infrastructure-level IO failure —
    disk full, EPERM, vanished mount, injected `persist` fault — and
    degraded to memory-only for the rest of the process (utils/durable.py
    latches it). Queries keep returning correct results; only the
    warm-restart story for that tier is lost until the disk is fixed."""


class ShuffleCorruptionError(RapidsTpuError):
    """A shuffle block frame failed its CRC32C integrity check (or its
    framing was unreadable). Carries the block and where the bytes came from;
    the fetch path refetches once before letting this fail the task."""

    def __init__(self, message: str, block=None, source: str = ""):
        super().__init__(message)
        self.block = block
        self.source = source


class ShuffleFetchFailedError(RapidsTpuError):
    """A remote shuffle fetch exhausted its retry budget (and any failover
    peers). Carries peer/block diagnostics for the task-level error report
    (the reference's RapidsShuffleFetchFailedException analog)."""

    def __init__(self, message: str, peer: str = "", blocks=(),
                 attempts: int = 0, cause: Exception = None):
        super().__init__(message)
        self.peer = peer
        self.blocks = tuple(blocks)
        self.attempts = attempts
        self.cause = cause


class QueryRejectedError(RapidsTpuError):
    """The admission layer shed this query under overload (queue depth or
    queue wait beyond the spark.rapids.tpu.sched.* bounds, or an injected
    sched.admit fault). Raised BEFORE admission: the query never touched
    the device, so the client can safely retry elsewhere/later."""

    def __init__(self, message: str, depth: int = -1, waited_s=None,
                 tenant: str = "", priority: int = 0):
        super().__init__(message)
        self.depth = depth
        self.waited_s = waited_s
        self.tenant = tenant
        self.priority = priority


class QueryCancelledError(RapidsTpuError):
    """The query's CancelToken was cancelled (client `cancel` op or an
    in-process cancel()); every cooperative cancellation point
    (sched.context.checkpoint) unwinds with this so admission tokens,
    budget reservations, parked batches and prefetch threads are
    reclaimed on the normal finally paths."""

    def __init__(self, message: str, query_id: str = ""):
        super().__init__(message)
        self.query_id = query_id


class DeadlineExceededError(RapidsTpuError, TimeoutError):
    """The query ran (or would sleep) past its deadline. Retry/backoff
    seams compute their next sleep as min(backoff, remaining deadline)
    and raise this instead of sleeping past it. Also a TimeoutError so
    generic timeout handlers keep working."""

    def __init__(self, message: str, deadline_s=None):
        super().__init__(message)
        self.deadline_s = deadline_s


class ServiceConnectionError(RapidsTpuError, ConnectionError):
    """A device-service connection died mid-request (worker crash, socket
    EOF, reset). Carries the endpoint and op so callers — the fleet
    gateway's failover loop above all — can decide whether the request is
    safe to re-dispatch: `phase` is "connect" when the request never
    reached the peer (always retryable), "send"/"recv" when it may have
    started executing (write plans must NOT be auto-retried then). Also a
    ConnectionError so pre-existing handlers keep working."""

    def __init__(self, message: str, endpoint: str = "", op: str = "",
                 phase: str = "recv", cause: Exception = None):
        super().__init__(message)
        self.endpoint = endpoint
        self.op = op
        self.phase = phase
        self.cause = cause

    @property
    def maybe_executed(self) -> bool:
        """True when the peer may have begun executing the request."""
        return self.phase != "connect"


class AdmissionTimeoutError(RapidsTpuError, TimeoutError):
    """The device-service admission semaphore did not grant a token within
    the requested timeout. Carries the server's held/waiting diagnostics
    (GpuSemaphore contention made visible). Also a TimeoutError so callers
    written against the old stringly reply keep working."""

    def __init__(self, message: str, held: int = -1, waiting: int = -1,
                 timeout_s=None):
        super().__init__(message)
        self.held = held
        self.waiting = waiting
        self.timeout_s = timeout_s
