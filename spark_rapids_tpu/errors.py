"""Framework exception types.

RetryOOM / SplitAndRetryOOM mirror the reference's per-thread retry exceptions raised by
RmmSpark (`RmmRapidsRetryIterator.scala:28-120` handles them); here they are raised by the
host-side budget tracker pre-flight instead of the allocator callback (ARCHITECTURE.md #6).
"""

from __future__ import annotations


class RapidsTpuError(Exception):
    """Base class for framework errors."""


class RetryOOM(RapidsTpuError):
    """Device memory pressure: block, spill, and retry the idempotent step."""


class SplitAndRetryOOM(RapidsTpuError):
    """Device memory pressure too high for retry alone: split the input and retry."""


class PlanNotFullyOnDevice(RapidsTpuError):
    """A zero-copy device handoff was requested but the plan has CPU
    sections; callers may fall back to host execution. Deliberately NOT a
    RuntimeError subclass so genuine runtime failures (XlaRuntimeError IS
    a RuntimeError) can never masquerade as this signal."""


class CpuFallbackRequired(RapidsTpuError):
    """A batch/op cannot execute on device; the planner/exec must take the host path."""


class StringWidthExceeded(CpuFallbackRequired):
    """A string batch exceeds spark.rapids.tpu.string.maxWidth for the fixed-width
    byte-matrix device layout; process this batch on host."""

    def __init__(self, width: int, limit: int):
        super().__init__(
            f"string batch max byte length {width} exceeds device layout limit "
            f"{limit} (spark.rapids.tpu.string.maxWidth)")
        self.width = width
        self.limit = limit


class DeviceStartupError(RapidsTpuError):
    """The device backend failed or HUNG during first touch (client init /
    device enumeration). Fatal for device execution: raised with diagnostics
    within the configured deadline instead of blocking the query forever —
    the analog of the reference's executor-startup inspection + fail-fast
    (`Plugin.scala:436-459`). The session can still run CPU-engine plans."""

    def __init__(self, message: str, diagnostics: dict | None = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


class AnsiViolation(RapidsTpuError):
    """Spark ANSI-mode runtime error (ArithmeticException analog): integral
    overflow, division by zero, or cast overflow under spark.sql.ansi.enabled."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message
