"""Device column vectors.

TPU-native counterpart of the reference's `GpuColumnVector.java` (Spark ColumnVector over
a cudf device column, conversions at `GpuColumnVector.java:637,669`): here a `Column` is a
pytree of JAX device arrays — a data buffer plus a validity mask — padded to a capacity
bucket (see padding.py). Strings use the fixed-width byte-matrix layout
(ARCHITECTURE.md #3) instead of cudf's offset+chars, because rectangular byte data maps
onto the VPU; conversion to/from Arrow offset+chars happens at the host boundary.

Semantics contract:
  * every array's leading dim is the batch *capacity*; rows >= the batch's logical
    `num_rows` are padding whose data AND validity contents are unspecified — kernels
    must mask with the batch row-mask wherever padding could leak into results;
  * `validity[i]` True means row i is non-null;
  * data values under null rows are unspecified (like Arrow), kernels must not rely.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from .padding import row_bucket, width_bucket

__all__ = ["Column", "make_column", "from_numpy", "from_arrow", "to_arrow"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """A device column: data + validity (+ lengths for strings, + children for
    nested types).

    dtype is static (pytree aux); arrays are leaves. For STRING columns `data` is
    uint8[cap, width] and `lengths` is int32[cap]; otherwise `lengths` is None and
    `data` is dtype[cap].

    Nested types (fixed-fanout layout, the string byte-matrix generalized):
      * array<elem>: `data` is int32[cap] per-row element counts; `children` is
        (elem_column,) whose arrays carry leading dims [cap, K] where K is the
        fanout bucket (width_bucket of the max list size);
      * struct<fields>: `data` is bool[cap] (a placeholder mirroring validity);
        `children` holds one column per field with leading dim [cap].
    Child leading dims always start with the parent capacity, so row-wise ops
    (gather/slice/compact/concat) apply uniformly down the tree.
    """

    dtype: T.DataType
    data: jnp.ndarray
    validity: jnp.ndarray
    lengths: Optional[jnp.ndarray] = None
    children: Optional[Tuple["Column", ...]] = None
    # long-string layout (columnar/strings.py): (blob uint8[B],
    # tail_start int32[cap]). blob is row-UNALIGNED — structural row ops
    # gather tail_start and pass the blob through untouched.
    overflow: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None

    # -- pytree ---------------------------------------------------------------
    def tree_flatten(self):
        leaves = [self.data, self.validity]
        has_len = self.lengths is not None
        if has_len:
            leaves.append(self.lengths)
        kids = tuple(self.children) if self.children else ()
        leaves.extend(kids)
        has_ovf = self.overflow is not None
        if has_ovf:
            leaves.extend(self.overflow)
        return tuple(leaves), (self.dtype, has_len, len(kids), has_ovf)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        dtype, has_len, nk, has_ovf = aux
        i = 3 if has_len else 2
        lengths = leaves[2] if has_len else None
        kids = tuple(leaves[i:i + nk]) if nk else None
        ovf = (leaves[i + nk], leaves[i + nk + 1]) if has_ovf else None
        return cls(dtype, leaves[0], leaves[1], lengths, kids, ovf)

    # -- shape ----------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    @property
    def string_width(self) -> int:
        assert self.is_string
        return int(self.data.shape[1])

    def device_memory_size(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.overflow is not None:
            n += self.overflow[0].size + self.overflow[1].size * 4
        for c in (self.children or ()):
            n += c.device_memory_size()
        return n

    # -- construction helpers -------------------------------------------------
    def with_validity(self, validity: jnp.ndarray) -> "Column":
        return Column(self.dtype, self.data, validity, self.lengths,
                      self.children, self.overflow)

    def repadded(self, new_cap: int) -> "Column":
        """Grow/shrink capacity (host-side op; used by coalesce/re-bucketing)."""
        cap = self.capacity
        if new_cap == cap:
            return self

        def fit(a):
            if new_cap > cap:
                pad = [(0, new_cap - cap)] + [(0, 0)] * (a.ndim - 1)
                return jnp.pad(a, pad)
            return a[:new_cap]

        return Column(self.dtype, fit(self.data), fit(self.validity),
                      None if self.lengths is None else fit(self.lengths),
                      None if self.children is None else tuple(
                          c.repadded(new_cap) for c in self.children),
                      None if self.overflow is None else
                      (self.overflow[0], fit(self.overflow[1])))

    # -- host boundary --------------------------------------------------------
    def to_numpy(self, num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """Return (values, valid_mask) sliced to the logical row count. String
        columns return an object array of Python str."""
        valid = np.asarray(self.validity[:num_rows])
        if self.is_string:
            from .strings import flatten_live_bytes
            flat, lens = flatten_live_bytes(self.data, self.lengths,
                                            self.overflow, valid, num_rows)
            offs = np.concatenate(([0], np.cumsum(lens, dtype=np.int64)))
            out = np.empty(num_rows, dtype=object)
            buf = flat.tobytes()
            for i in range(num_rows):
                out[i] = buf[offs[i]:offs[i + 1]].decode("utf-8", "replace") \
                    if valid[i] else None
            return out, valid
        return np.asarray(self.data[:num_rows]), valid


def make_column(dtype: T.DataType, data, validity, lengths=None) -> Column:
    return Column(dtype, data, validity, lengths)


def _pad_to(arr: np.ndarray, cap: int) -> np.ndarray:
    if arr.shape[0] == cap:
        return arr
    pad = [(0, cap - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def from_numpy(dtype: T.DataType, values: np.ndarray,
               valid: Optional[np.ndarray] = None,
               capacity: Optional[int] = None) -> Tuple[Column, int]:
    """Build a device Column from host values; returns (column, num_rows)."""
    n = len(values)
    cap = capacity or row_bucket(n)
    if valid is None:
        valid = np.ones(n, dtype=bool)
    valid = _pad_to(np.asarray(valid, dtype=bool), cap)

    if isinstance(dtype, T.StringType):
        lens = np.zeros(n, dtype=np.int32)
        enc = []
        for i, v in enumerate(values):
            b = v.encode("utf-8") if isinstance(v, str) else (v or b"")
            enc.append(b)
            lens[i] = len(b)
        from .strings import build_string_leaves
        databuf = np.frombuffer(b"".join(enc), np.uint8) if enc else \
            np.zeros(0, np.uint8)
        offsets = np.concatenate(([0], np.cumsum(lens, dtype=np.int64)))
        head, lens_p, ovf = build_string_leaves(databuf, offsets, lens, cap)
        return Column(dtype, jnp.asarray(head), jnp.asarray(valid),
                      jnp.asarray(lens_p), None,
                      None if ovf is None else
                      (jnp.asarray(ovf[0]), jnp.asarray(ovf[1]))), n

    npdt = dtype.np_dtype
    if npdt is None:
        raise TypeError(f"cannot build device column for {dtype}")
    vals = _pad_to(np.ascontiguousarray(values, dtype=npdt), cap)
    return Column(dtype, jnp.asarray(vals), jnp.asarray(valid)), n


def from_arrow(arr, capacity: Optional[int] = None) -> Tuple[Column, int]:
    """Arrow array -> device Column. Vectorized offset+chars -> byte-matrix repack
    for strings (host boundary; native/ carries the C++ fast path)."""
    import pyarrow as pa
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dtype = T.from_arrow(arr.type)
    n = len(arr)
    cap = capacity or row_bucket(n)
    valid = np.ones(n, dtype=bool) if arr.null_count == 0 else \
        np.asarray(arr.is_valid())

    if isinstance(dtype, T.StringType):
        arr = arr.cast(pa.large_string()) if pa.types.is_string(arr.type) else arr
        buffers = arr.buffers()
        offsets = np.frombuffer(buffers[1], dtype=np.int64,
                                count=n + 1, offset=arr.offset * 8)
        databuf = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] else \
            np.zeros(0, np.uint8)
        lens_raw = np.diff(offsets).astype(np.int32)
        # null slots may carry garbage lengths in theory; normalize to 0
        lens = np.where(valid, lens_raw, 0).astype(np.int32)
        from .strings import build_string_leaves, head_width
        mx = int(lens.max()) if n and lens.size else 0
        if mx <= head_width():
            w = width_bucket(max(mx, 1))
            from ..native import runtime as _native
            chars = np.zeros((cap, w), dtype=np.uint8)
            # native path requires every raw slot (incl. nulls) to fit
            native = _native.offsets_to_matrix(databuf, offsets, w,
                                               out=chars) \
                if n and _native.available() and int(lens_raw.max()) <= w \
                else None
            if native is not None:
                if not valid.all():  # nulls are sparse: zero just those rows
                    chars[:n][~valid] = 0
            else:
                if n:
                    row_id = np.repeat(np.arange(n), lens)
                    if row_id.size:
                        out_starts = np.concatenate(([0],
                                                     np.cumsum(lens)[:-1]))
                        within = np.arange(row_id.size) - np.repeat(
                            out_starts, lens)
                        src = np.repeat(offsets[:-1], lens) + within
                        chars[row_id, within] = databuf[src]
            return Column(dtype, jnp.asarray(chars),
                          jnp.asarray(_pad_to(valid, cap)),
                          jnp.asarray(_pad_to(lens, cap))), n
        # long strings: chunked head+blob layout, no cap x width blow-up
        head, lens_p, ovf = build_string_leaves(databuf, offsets, lens, cap)
        return Column(dtype, jnp.asarray(head),
                      jnp.asarray(_pad_to(valid, cap)),
                      jnp.asarray(lens_p), None,
                      (jnp.asarray(ovf[0]), jnp.asarray(ovf[1]))), n

    if isinstance(dtype, T.DecimalType) and \
            dtype.precision > T.DecimalType.MAX_LONG_DIGITS:
        # decimal128: two int64 limbs per row, [cap, 2] (see expr/decimal128)
        from ..expr.decimal128 import split_int, unscaled_int
        limbs = np.zeros((n, 2), np.int64)
        for i, v in enumerate(arr):
            if v.is_valid:
                limbs[i] = split_int(unscaled_int(v.as_py(), dtype.scale))
        limbs = _pad_to(limbs, cap)
        return Column(dtype, jnp.asarray(limbs),
                      jnp.asarray(_pad_to(valid, cap))), n
    npdt = dtype.np_dtype
    if npdt is None:
        if dtype.is_nested:
            # array/struct/map: build the exact-length host form, then pad
            # the leading dim of every buffer to the capacity bucket and ship
            from ..cpu.hostbatch import host_vec_from_arrow, vec_map_arrays
            hv = host_vec_from_arrow(arr)

            def pad_ship(leaf):
                return jnp.asarray(_pad_to(np.asarray(leaf), cap))

            return vec_map_arrays(hv, pad_ship).to_column(), n
        raise TypeError(
            f"type not yet device-backed: {arr.type} "
            "(binary needs the string byte-matrix path)")
    if isinstance(dtype, T.DecimalType):
        from ..expr.decimal128 import unscaled_int
        vals = np.array([unscaled_int(v.as_py(), dtype.scale)
                         if v.is_valid else 0
                         for v in arr], dtype=np.int64)
    elif isinstance(dtype, (T.TimestampType, T.DateType)):
        ints = arr.cast(pa.int64() if isinstance(dtype, T.TimestampType)
                        else pa.int32())
        # fill nulls BEFORE to_numpy: a nullable int array otherwise converts via
        # float64, silently corrupting values beyond 2^53
        vals = ints.fill_null(0).to_numpy(zero_copy_only=False)
    elif arr.null_count:
        zero = False if isinstance(dtype, T.BooleanType) else 0
        vals = arr.fill_null(zero).to_numpy(zero_copy_only=False)
    else:
        vals = arr.to_numpy(zero_copy_only=False)
    vals = np.ascontiguousarray(vals)
    # float conversions can still carry NaN under null slots; zero them
    if np.issubdtype(vals.dtype, np.floating) and not valid.all():
        vals = np.where(valid, vals, 0.0)
    vals = _pad_to(vals.astype(npdt, copy=False), cap)
    return Column(dtype, jnp.asarray(vals), jnp.asarray(_pad_to(valid, cap))), n


def to_arrow(col: Column, num_rows: int):
    """Device Column -> Arrow array (host boundary)."""
    import pyarrow as pa
    if isinstance(col.dtype, T.NullType):
        return pa.nulls(num_rows)
    if col.children is not None:
        from ..cpu.hostbatch import host_vec_to_arrow, vec_map_arrays
        from ..expr.base import Vec
        hv = vec_map_arrays(Vec.from_column(col),
                            lambda a: np.asarray(a)[:num_rows])
        return host_vec_to_arrow(hv, num_rows)
    valid = np.asarray(col.validity[:num_rows])
    mask = ~valid
    if col.is_string:
        from .strings import flatten_live_bytes
        flat, lens32 = flatten_live_bytes(col.data, col.lengths,
                                          col.overflow, valid, num_rows)
        lens = lens32.astype(np.int64)
        offsets = np.concatenate(([0], np.cumsum(lens)))
        return pa.Array.from_buffers(
            pa.large_string(), num_rows,
            [pa.py_buffer(np.packbits(valid, bitorder="little").tobytes()),
             pa.py_buffer(offsets.astype(np.int64).tobytes()),
             pa.py_buffer(flat.tobytes())],
            null_count=int(mask.sum())).cast(pa.string())
    vals = np.asarray(col.data[:num_rows])
    at = T.to_arrow(col.dtype)
    if isinstance(col.dtype, T.DecimalType):
        from ..expr.decimal128 import join_int, to_decimal
        if col.dtype.precision > T.DecimalType.MAX_LONG_DIGITS:
            py = [(to_decimal(join_int(int(v[0]), int(v[1])),
                              col.dtype.scale) if m else None)
                  for v, m in zip(vals, valid)]
            return pa.array(py, type=at)
        py = [(to_decimal(int(v), col.dtype.scale) if m else None)
              for v, m in zip(vals, valid)]
        return pa.array(py, type=at)
    return pa.array(vals, type=at, mask=mask if mask.any() else None)
