"""Columnar batches.

TPU counterpart of Spark's `ColumnarBatch` carrying `GpuColumnVector`s (reference
`GpuColumnVector.java:637` from(ColumnarBatch) / `:669` from(Table, DataType[])). A
`ColumnarBatch` here is a pytree: a tuple of `Column`s plus a traced scalar `num_rows`,
with the schema static. All columns share one capacity bucket. The traced row count is
what lets filters/joins change cardinality without recompiling (ARCHITECTURE.md #1).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from .column import Column, from_arrow as col_from_arrow, from_numpy as col_from_numpy, \
    to_arrow as col_to_arrow
from .padding import row_bucket

__all__ = ["Schema", "ColumnarBatch", "batch_from_arrow", "batch_to_arrow",
           "batch_from_dict", "empty_batch"]


@dataclasses.dataclass(frozen=True)
class Schema:
    names: Tuple[str, ...]
    types: Tuple[T.DataType, ...]

    def __post_init__(self):
        assert len(self.names) == len(self.types)

    def __len__(self):
        return len(self.names)

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def field(self, i: int) -> Tuple[str, T.DataType]:
        return self.names[i], self.types[i]

    def to_arrow(self):
        import pyarrow as pa
        return pa.schema([pa.field(n, T.to_arrow(t))
                          for n, t in zip(self.names, self.types)])

    @staticmethod
    def from_arrow(schema) -> "Schema":
        return Schema(tuple(schema.names),
                      tuple(T.from_arrow(f.type) for f in schema))

    def __repr__(self):
        inner = ", ".join(f"{n}: {t.simple_string()}"
                          for n, t in zip(self.names, self.types))
        return f"Schema({inner})"


def join_output_schema(left: Schema, right: Schema, join_type: str) -> Schema:
    """Output schema of a join — the ONE definition shared by the CPU oracle
    and both device join execs so they can never drift. semi/anti project the
    left side; existence appends the bool `exists` flag; everything else
    (inner/cross/left/right/full) is the combined row."""
    if join_type in ("semi", "anti"):
        return left
    if join_type == "existence":
        return Schema(left.names + ("exists",),
                      left.types + (T.BooleanType(),))
    return Schema(left.names + right.names, left.types + right.types)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ColumnarBatch:
    """columns: per-field device Columns; num_rows: traced int32 scalar."""

    schema: Schema
    columns: Tuple[Column, ...]
    num_rows: jnp.ndarray  # int32 scalar (device)

    def tree_flatten(self):
        return (tuple(self.columns), self.num_rows), self.schema

    @classmethod
    def tree_unflatten(cls, schema, leaves):
        columns, num_rows = leaves
        return cls(schema, tuple(columns), num_rows)

    # ------------------------------------------------------------------
    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        return 0

    def row_count(self) -> int:
        """Host-synchronizing logical row count (use only on host paths)."""
        return int(self.num_rows)

    def row_mask(self) -> jnp.ndarray:
        """bool[cap]: True for live (non-padding) rows. Fused away by XLA."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def column(self, i: int) -> Column:
        return self.columns[i]

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    def with_columns(self, schema: Schema, columns: Sequence[Column],
                     num_rows=None) -> "ColumnarBatch":
        return ColumnarBatch(schema, tuple(columns),
                             self.num_rows if num_rows is None else num_rows)

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch(
            Schema(tuple(self.schema.names[i] for i in indices),
                   tuple(self.schema.types[i] for i in indices)),
            tuple(self.columns[i] for i in indices), self.num_rows)

    def repadded(self, new_cap: int) -> "ColumnarBatch":
        return ColumnarBatch(self.schema,
                             tuple(c.repadded(new_cap) for c in self.columns),
                             self.num_rows)


def batch_from_arrow(table, capacity: Optional[int] = None) -> ColumnarBatch:
    """pyarrow Table/RecordBatch -> device ColumnarBatch (the H2D boundary)."""
    n = table.num_rows
    cap = capacity or row_bucket(n, op="scan")
    cols: List[Column] = []
    for name in table.schema.names:
        col, _ = col_from_arrow(table.column(name), capacity=cap)
        cols.append(col)
    schema = Schema.from_arrow(table.schema)
    return ColumnarBatch(schema, tuple(cols), jnp.asarray(n, dtype=jnp.int32))


def batch_from_dict(data: dict, types_map: Optional[dict] = None,
                    capacity: Optional[int] = None) -> ColumnarBatch:
    """Convenience constructor from {name: np.ndarray/list} (tests, data_gen)."""
    names = tuple(data.keys())
    n = len(next(iter(data.values()))) if data else 0
    cap = capacity or row_bucket(n, op="scan")
    cols = []
    tps = []
    for name in names:
        vals = data[name]
        if types_map and name in types_map:
            dt = types_map[name]
        else:
            dt = _infer_type(vals)
        valid = None
        if isinstance(vals, (list, tuple)):
            valid = np.array([v is not None for v in vals])
            if isinstance(dt, T.StringType):
                pass
            else:
                vals = np.array([0 if v is None else v for v in vals],
                                dtype=dt.np_dtype)
        col, _ = col_from_numpy(dt, vals if not isinstance(vals, (list, tuple))
                                else list(vals), valid, capacity=cap)
        cols.append(col)
        tps.append(dt)
    return ColumnarBatch(Schema(names, tuple(tps)), tuple(cols),
                         jnp.asarray(n, dtype=jnp.int32))


def _infer_type(vals) -> T.DataType:
    if isinstance(vals, np.ndarray):
        k = vals.dtype
        m = {np.dtype(np.bool_): T.BOOLEAN, np.dtype(np.int8): T.BYTE,
             np.dtype(np.int16): T.SHORT, np.dtype(np.int32): T.INT,
             np.dtype(np.int64): T.LONG, np.dtype(np.float32): T.FLOAT,
             np.dtype(np.float64): T.DOUBLE}
        if k in m:
            return m[k]
        raise TypeError(f"cannot infer type for dtype {k}")
    for v in vals:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BOOLEAN
        if isinstance(v, int):
            return T.LONG
        if isinstance(v, float):
            return T.DOUBLE
        if isinstance(v, str):
            return T.STRING
    return T.NULL


def batch_to_arrow(batch: ColumnarBatch):
    """Device ColumnarBatch -> pyarrow Table (the D2H boundary)."""
    import pyarrow as pa
    n = batch.row_count()
    arrays = [col_to_arrow(c, n) for c in batch.columns]
    return pa.table(arrays, schema=batch.schema.to_arrow())


def empty_batch(schema: Schema, capacity: int = 0) -> ColumnarBatch:
    from ..expr.base import zero_vec
    cap = row_bucket(max(capacity, 1))
    cols = tuple(zero_vec(jnp, dt, (cap,)).to_column()
                 for dt in schema.types)
    return ColumnarBatch(schema, cols, jnp.asarray(0, jnp.int32))
