"""Bucketed-padding discipline.

XLA compiles one program per shape, so device batches are padded to a small set of
capacity buckets; the logical row count travels as a traced scalar. This keeps the number
of distinct compiled programs logarithmic in batch-size range (the reference has no such
concern — CUDA kernels take runtime sizes — making this the first genuinely TPU-specific
design point, see ARCHITECTURE.md #1).

Hot-path discipline: `row_bucket` sits under every batch materialization, so
the conf reads (minRows/growth) are memoized per conf-generation instead of
re-walking the registry per call; `invalidate_cache()` is the hook the
compile service's bucket tuner (and `TpuConf.set` on padding keys) uses to
drop the memo. The tuner can also install a LEARNED ladder
(`install_tuned_buckets`): observed-workload capacities that replace the
geometric ladder within their range — fewer distinct buckets (fewer XLA
programs) with waste bounded by the observed clusters. Sizes beyond the
ladder fall back to geometric growth from its top rung."""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from ..config import get_default_conf

LANE = 128

_lock = threading.Lock()
_generation = 0
_cached_params: Optional[Tuple[int, float, int]] = None  # (minRows, growth, gen)
_tuned_ladder: Tuple[int, ...] = ()
# compile-service tuner hook: called (op, n) per bucket decision when set
_observer: Optional[Callable[[Optional[str], int], None]] = None


def invalidate_cache() -> None:
    """Drop the memoized padding conf (conf change / tuner retune)."""
    global _generation, _cached_params
    with _lock:
        _generation += 1
        _cached_params = None


def install_tuned_buckets(caps) -> None:
    """Install a learned capacity ladder (ascending, lane-aligned; empty
    clears back to the pure geometric ladder). Compile-service tuner entry
    point."""
    global _tuned_ladder
    aligned = sorted({((int(c) + LANE - 1) // LANE) * LANE
                      for c in caps if int(c) > 0})
    with _lock:
        _tuned_ladder = tuple(aligned)
    invalidate_cache()


def tuned_buckets() -> Tuple[int, ...]:
    return _tuned_ladder


def set_bucket_observer(fn: Optional[Callable]) -> None:
    """Register the tuner's observation hook (None disables)."""
    global _observer
    _observer = fn


def _params() -> Tuple[int, float]:
    global _cached_params
    p = _cached_params
    if p is not None and p[2] == _generation:
        return p[0], p[1]
    conf = get_default_conf()
    p = (conf.get("spark.rapids.tpu.padding.minRows"),
         max(1.25, conf.get("spark.rapids.tpu.padding.growth")),
         _generation)
    with _lock:
        _cached_params = p
    return p[0], p[1]


def row_bucket(n: int, min_rows: int = 0, op: str = None) -> int:
    """Smallest capacity bucket >= n. With a tuned ladder installed, the
    first ladder rung >= n wins; otherwise (and beyond the ladder) buckets
    start at max(minRows, LANE) and grow by spark.rapids.tpu.padding.growth
    (lane-aligned), default 2x. `op` attributes the observation to an
    operator for the bucket tuner."""
    obs = _observer
    if obs is not None:
        obs(op, n)
    conf_min, growth = _params()
    if min_rows <= 0:
        min_rows = conf_min
    floor = max(min_rows, LANE)
    for rung in _tuned_ladder:
        if rung >= n and rung >= floor:
            return rung
    cap = floor
    if _tuned_ladder and _tuned_ladder[-1] > cap:
        cap = _tuned_ladder[-1]
    while cap < n:
        cap = ((int(cap * growth) + LANE - 1) // LANE) * LANE
    return cap


def width_bucket(w: int) -> int:
    """String byte-matrix width bucket: multiples of 8 up to a lane, then powers of two
    (keeps the trailing dim friendly to (8,128) tiling without exploding memory for
    short strings)."""
    if w <= 8:
        return 8
    if w <= LANE:
        return (w + 7) & ~7
    cap = LANE
    while cap < w:
        cap <<= 1
    return cap
