"""Bucketed-padding discipline.

XLA compiles one program per shape, so device batches are padded to a small set of
capacity buckets; the logical row count travels as a traced scalar. This keeps the number
of distinct compiled programs logarithmic in batch-size range (the reference has no such
concern — CUDA kernels take runtime sizes — making this the first genuinely TPU-specific
design point, see ARCHITECTURE.md #1)."""

from __future__ import annotations

from ..config import get_default_conf

LANE = 128


def row_bucket(n: int, min_rows: int = 0) -> int:
    """Smallest capacity bucket >= n: buckets start at max(minRows, LANE) and grow by
    spark.rapids.tpu.padding.growth (lane-aligned), default 2x."""
    conf = get_default_conf()
    if min_rows <= 0:
        min_rows = conf.get("spark.rapids.tpu.padding.minRows")
    growth = max(1.25, conf.get("spark.rapids.tpu.padding.growth"))
    cap = max(min_rows, LANE)
    while cap < n:
        cap = ((int(cap * growth) + LANE - 1) // LANE) * LANE
    return cap


def width_bucket(w: int) -> int:
    """String byte-matrix width bucket: multiples of 8 up to a lane, then powers of two
    (keeps the trailing dim friendly to (8,128) tiling without exploding memory for
    short strings)."""
    if w <= 8:
        return 8
    if w <= LANE:
        return (w + 7) & ~7
    cap = LANE
    while cap < w:
        cap <<= 1
    return cap
