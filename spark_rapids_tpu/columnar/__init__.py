from .column import Column, from_arrow, from_numpy, make_column, to_arrow  # noqa: F401
from .batch import (Schema, ColumnarBatch, batch_from_arrow, batch_from_dict,  # noqa: F401
                    batch_to_arrow, empty_batch)
from .padding import row_bucket, width_bucket, LANE  # noqa: F401
