"""Chunked long-string device layout: head byte-matrix + shared tail blob.

The fixed-width byte matrix (column.py) pays `cap x width` bytes where width
is the bucket of the LONGEST value — one 8KB string widens every row's slot
(the round-2/3 "width cliff"; the reference never has it because libcudf
strings are offset+data, consumed throughout `stringFunctions.scala:1`).

This module is the TPU-shaped offset+data equivalent:

  head:       uint8[cap, W0]  — first W0 bytes of every row (W0 = the
              `spark.rapids.tpu.string.headWidth` bucket, default 256).
              Rectangular: every existing elementwise/VPU kernel shape.
  blob:       uint8[B]        — tail bytes (beyond W0) of all rows,
              concatenated in row order; B is a capacity bucket. The blob is
              SHARED and append-only within a batch lineage.
  tail_start: int32[cap]      — row-aligned offset of each row's tail in the
              blob (undefined where lengths <= W0). Row-wise structural ops
              (filter compact, join gather, sort reorder, slice) gather
              tail_start exactly like any other row buffer and leave the
              blob untouched — a row move is O(1) regardless of string size.
  lengths:    int32[cap]      — FULL byte length (head + tail), same buffer
              the flat layout uses.

A column with `overflow=(blob, tail_start)` is a "long-string" column. Ops
that only move rows work unchanged; ops that must see all bytes either
assemble on host (CPU engine / host boundary) or raise CpuFallbackRequired
(device engine, per-op fallback — the same discipline the scan paths use).
The blob carries dead bytes after filters; `compact_tails` garbage-collects
at coalesce points, and a batch whose live rows all fit the head width heals
back to the plain flat layout (exec/coalesce.rebucket_string_widths).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..config import get_default_conf
from .padding import width_bucket

__all__ = ["head_width", "blob_bucket", "build_string_leaves",
           "assemble_matrix", "compact_tails", "tails_from_matrix",
           "flatten_live_bytes", "segment_arange"]


def segment_arange(lens: "np.ndarray") -> "np.ndarray":
    """[0..lens[0]), [0..lens[1]), ... concatenated — the within-segment
    position stream every blob gather/scatter in this layout uses."""
    total = int(lens.sum())
    out = np.arange(total, dtype=np.int64)
    seg_starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return out - np.repeat(seg_starts, lens)


def head_width(conf=None) -> int:
    conf = conf or get_default_conf()
    return width_bucket(int(conf.get("spark.rapids.tpu.string.headWidth")))


def blob_bucket(nbytes: int) -> int:
    """Blob capacity bucket: 1KB chunks, power-of-two chunk counts — the
    fixed-size-chunk allocation granularity of the layout."""
    chunks = max(1, -(-nbytes // 1024))
    p = 1
    while p < chunks:
        p *= 2
    return p * 1024


def build_string_leaves(
        databuf: np.ndarray, offsets: np.ndarray, lens: np.ndarray,
        cap: int, conf=None,
) -> Tuple[np.ndarray, np.ndarray, Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Arrow-style (flat bytes, int64 offsets[n+1], int32 lens[n]) -> layout
    leaves (head[cap, W], lengths[cap], overflow|None). Used by the host
    boundary (from_arrow), the shuffle deserializer, and tests.

    Short columns (max len <= head width) produce the plain flat layout
    (overflow None) at the exact width bucket — byte-identical to the
    historical behavior, so short strings pay nothing."""
    n = len(lens)
    mx = int(lens.max()) if n else 0
    hw = head_width(conf)
    w = width_bucket(max(mx, 1))
    lens = np.ascontiguousarray(lens, dtype=np.int32)

    def matrix(width, clamp):
        chars = np.zeros((cap, width), dtype=np.uint8)
        if n:
            eff = np.minimum(lens, clamp) if clamp else lens
            row_id = np.repeat(np.arange(n), eff)
            if row_id.size:
                starts = np.concatenate(([0], np.cumsum(eff)[:-1]))
                within = np.arange(row_id.size) - np.repeat(starts, eff)
                src = np.repeat(np.asarray(offsets[:-1], np.int64), eff) \
                    + within
                chars[row_id, within] = databuf[src]
        return chars

    if mx <= hw:
        return matrix(w, None), _pad_rows(lens, cap), None

    head = matrix(hw, hw)
    tail_lens = np.maximum(lens - hw, 0).astype(np.int64)
    total = int(tail_lens.sum())
    blob = np.zeros(blob_bucket(total), np.uint8)
    tail_start = np.zeros(cap, np.int32)
    starts = np.concatenate(([0], np.cumsum(tail_lens)[:-1]))
    tail_start[:n] = starts.astype(np.int32)
    row_id = np.repeat(np.arange(n), tail_lens)
    if row_id.size:
        within = np.arange(row_id.size) - np.repeat(starts, tail_lens)
        src = np.repeat(np.asarray(offsets[:-1], np.int64) + hw, tail_lens) \
            + within
        blob[np.repeat(starts, tail_lens) + within] = databuf[src]
    return head, _pad_rows(lens, cap), (blob, tail_start)


def _pad_rows(a: np.ndarray, cap: int) -> np.ndarray:
    if a.shape[0] == cap:
        return a
    return np.pad(a, (0, cap - a.shape[0]))


def assemble_matrix(head: np.ndarray, lengths: np.ndarray,
                    overflow, num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: (full byte matrix [num_rows, maxw], lengths[num_rows]).
    The per-op fallback materialization — only ever called on host paths
    (to_arrow / CPU assembly); device ops that need it fall back instead."""
    head = np.asarray(head)[:num_rows]
    lens = np.asarray(lengths)[:num_rows].astype(np.int32)
    if overflow is None:
        return head, lens
    blob = np.asarray(overflow[0])
    tail_start = np.asarray(overflow[1])[:num_rows].astype(np.int64)
    hw = head.shape[1]
    mx = int(lens.max()) if num_rows else 0
    out = np.zeros((num_rows, max(mx, hw)), np.uint8)
    out[:, :hw] = head
    tail_lens = np.maximum(lens - hw, 0).astype(np.int64)
    row_id = np.repeat(np.arange(num_rows), tail_lens)
    if row_id.size:
        starts = np.repeat(tail_start, tail_lens)
        within = np.arange(row_id.size) - np.repeat(
            np.concatenate(([0], np.cumsum(tail_lens)[:-1])), tail_lens)
        out[row_id, hw + within] = blob[starts + within]
    return out, lens


def flatten_live_bytes(data, lengths, overflow, valid,
                       num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: exact concatenated live bytes + per-row lengths, with NO
    dense [n, maxw] intermediate for overflow columns (head rows and blob
    spans are scattered straight into the output). The one implementation
    behind to_arrow, the shuffle varlen wire, and host transitions."""
    n = num_rows
    lens = np.asarray(lengths)[:n].astype(np.int32)
    if valid is not None:
        lens = np.where(np.asarray(valid)[:n], lens, 0)
    head = np.asarray(data)[:n]
    hw = head.shape[1] if head.ndim == 2 else 0
    if overflow is None:
        if not (n and hw):
            return np.zeros(0, np.uint8), lens
        keep = np.arange(hw)[None, :] < lens[:, None]
        return head[keep], lens
    blob = np.asarray(overflow[0])
    tail_start = np.asarray(overflow[1])[:n].astype(np.int64)
    head_lens = np.minimum(lens, hw).astype(np.int64)
    tail_lens = (lens - head_lens).astype(np.int64)
    out = np.zeros(int(lens.sum()), np.uint8)
    out_off = np.concatenate(([0], np.cumsum(lens, dtype=np.int64)[:-1]))
    hrow = np.repeat(np.arange(n), head_lens)
    if hrow.size:
        hstarts = np.concatenate(([0], np.cumsum(head_lens)[:-1]))
        hwithin = np.arange(hrow.size) - np.repeat(hstarts, head_lens)
        out[np.repeat(out_off, head_lens) + hwithin] = head[hrow, hwithin]
    trow = np.repeat(np.arange(n), tail_lens)
    if trow.size:
        tstarts = np.concatenate(([0], np.cumsum(tail_lens)[:-1]))
        twithin = np.arange(trow.size) - np.repeat(tstarts, tail_lens)
        src = np.repeat(tail_start, tail_lens) + twithin
        out[np.repeat(out_off + head_lens, tail_lens) + twithin] = blob[src]
    return out, lens


def compact_tails(lengths: np.ndarray, overflow, live: np.ndarray,
                  hw: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Host-side blob GC: rebuild the blob holding only live rows' tails.
    Returns new (blob, tail_start) with tail_start aligned to the SAME row
    capacity. Caller decides when (coalesce points, serializer)."""
    lens = np.asarray(lengths)
    blob = np.asarray(overflow[0])
    tail_start = np.asarray(overflow[1]).astype(np.int64)
    cap = lens.shape[0]
    tail_lens = np.where(np.asarray(live),
                         np.maximum(lens.astype(np.int64) - hw, 0), 0)
    total = int(tail_lens.sum())
    new_blob = np.zeros(blob_bucket(total), np.uint8)
    new_start = np.zeros(cap, np.int32)
    starts = np.concatenate(([0], np.cumsum(tail_lens)[:-1]))
    new_start[:] = starts.astype(np.int32)
    row_id = np.repeat(np.arange(cap), tail_lens)
    if row_id.size:
        within = np.arange(row_id.size) - np.repeat(starts, tail_lens)
        src = np.repeat(tail_start, tail_lens) + within
        new_blob[np.repeat(starts, tail_lens) + within] = blob[src]
    return new_blob, new_start


def tails_from_matrix(data, w0: int):
    """Jit-safe: convert a wide flat matrix [cap, W] (W > w0) into overflow
    form WITHOUT host sync: head = data[:, :w0], blob = the rectangular tail
    region flattened (each row's tail slot is (W - w0) wide, so tail_start
    is a static stride — dead bytes beyond each row's true tail are padding
    the blob GC reclaims later). Works under tracing (static shapes only).

    Returns (head, blob, tail_start)."""
    import jax.numpy as jnp
    xp = jnp if not isinstance(data, np.ndarray) else np
    cap, w = data.shape
    stride = w - w0
    head = data[:, :w0]
    blob = data[:, w0:].reshape(cap * stride)
    tail_start = (xp.arange(cap, dtype=np.int32) * np.int32(stride))
    return head, blob, tail_start
