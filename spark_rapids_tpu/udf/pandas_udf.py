"""Arrow-based pandas UDFs.

Reference: `GpuArrowEvalPythonExec.scala:235` + `BatchQueue` (`:174`),
`PythonWorkerSemaphore.scala`, worker-side RMM init (`python/rapids/
daemon.py`, `worker.py`). The reference crosses JVM -> forked python workers
over Arrow IPC; this framework already IS python, so the "worker" is an
in-process thread pool bounded by a semaphore (the PythonWorkerSemaphore
role), and the Arrow hop becomes device->host conversion around the user
function. The expression works on both engines: the CPU engine calls the
function on exact-length pandas data; the device path pulls the batch to
host, runs the function, and pushes the result back padded."""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

import numpy as np

from .. import types as T
from ..config import get_default_conf
from ..expr.base import Expression, EvalContext, Vec

__all__ = ["PandasUDF", "pandas_udf", "PythonWorkerSemaphore"]


class PythonWorkerSemaphore:
    """Bounds concurrent python UDF evaluations (PythonWorkerSemaphore.scala:
    limits how many workers share the device). REENTRANT per thread: a
    task already holding a permit re-enters freely, so stacked pandas
    execs (map_in_pandas over map_in_pandas, a scalar PandasUDF inside a
    grouped fn) pulling their child iterators inside the outer's permit
    cannot deadlock — the nesting is one task, one worker."""

    _instance: Optional["PythonWorkerSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, permits: int):
        self._sem = threading.Semaphore(permits)
        self.permits = permits
        self._tls = threading.local()

    @classmethod
    def get(cls, permits: Optional[int] = None) -> "PythonWorkerSemaphore":
        """Process-wide semaphore sized by the caller's conf; resized (when
        idle-compatible) if a session with a different limit comes along."""
        if permits is None:
            permits = get_default_conf().get(
                "spark.rapids.sql.concurrentGpuTasks")
        with cls._lock:
            if cls._instance is None or cls._instance.permits != permits:
                cls._instance = PythonWorkerSemaphore(permits)
            return cls._instance

    def __enter__(self):
        depth = getattr(self._tls, "depth", 0)
        if depth == 0:
            self._sem.acquire()
        self._tls.depth = depth + 1
        return self

    def __exit__(self, *exc):
        depth = getattr(self._tls, "depth", 0)
        if depth <= 1:
            self._tls.depth = 0
            if depth == 1:  # a foreign-thread exit never acquired: no-op
                self._sem.release()
        else:
            self._tls.depth = depth - 1


class PandasUDF(Expression):
    """fn receives one pandas Series per argument (nulls as NaN/None) and must
    return a Series/array of the declared return type."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: Sequence[Expression]):
        super().__init__(list(children))
        self.fn = fn
        self._dtype = return_type

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    deterministic = False  # black box: keep the planner conservative

    def _compute(self, ctx: EvalContext, *vecs: Vec) -> Vec:
        import jax
        if ctx.is_device and isinstance(vecs[0].data, jax.core.Tracer):
            raise RuntimeError(
                "PandasUDF cannot run inside a jitted kernel; the exec "
                "evaluates it at the host boundary (planner arranges this)")
        import pandas as pd
        n = int(np.asarray(vecs[0].validity).shape[0]) if ctx.row_mask is None \
            else int(np.asarray(ctx.row_mask).sum())
        series = [pd.Series(_vec_to_host(v, n),
                            dtype=object if v.is_string else None)
                  for v in vecs]
        permits = ctx.conf.get("spark.rapids.sql.concurrentGpuTasks") \
            if ctx.conf is not None else None
        with PythonWorkerSemaphore.get(permits):
            out = self.fn(*series)
        return _host_to_vec(ctx.xp, np.asarray(pd.Series(out)), self._dtype,
                            vecs[0].validity, n)

    def __repr__(self):
        return f"PandasUDF:{getattr(self.fn, '__name__', '<fn>')}" \
               f"({', '.join(map(repr, self.children))})"


def pandas_udf(return_type: T.DataType):
    """Decorator: `@pandas_udf(T.DOUBLE)` then call with column exprs."""

    def deco(fn: Callable):
        def wrapper(*args: Expression) -> PandasUDF:
            return PandasUDF(fn, return_type, list(args))

        wrapper.fn = fn
        return wrapper

    return deco


def _vec_to_host(v: Vec, n: int):
    valid = np.asarray(v.validity)[:n]
    if v.is_string:
        chars = np.asarray(v.data)[:n]
        lens = np.asarray(v.lengths)[:n]
        return [bytes(chars[i, :lens[i]]).decode("utf-8", "replace")
                if valid[i] else None for i in range(n)]
    data = np.asarray(v.data)[:n]
    if np.issubdtype(data.dtype, np.floating):
        return np.where(valid, data, np.nan)
    if valid.all():
        return data
    out = data.astype(object)
    out[~valid] = None
    return out


def _host_to_vec(xp, arr: np.ndarray, dtype: T.DataType, validity_like,
                 n: int) -> Vec:
    cap = np.asarray(validity_like).shape[0]
    if isinstance(dtype, T.StringType):
        from ..columnar.padding import width_bucket
        enc = [x.encode("utf-8") if isinstance(x, str) else None for x in arr]
        w = width_bucket(max((len(b) for b in enc if b is not None),
                             default=1) or 1)
        data = np.zeros((cap, w), np.uint8)
        lens = np.zeros(cap, np.int32)
        valid = np.zeros(cap, bool)
        for i, b in enumerate(enc):
            if b is None:
                continue
            data[i, :len(b)] = np.frombuffer(b, np.uint8)
            lens[i] = len(b)
            valid[i] = True
        return Vec(dtype, xp.asarray(data), xp.asarray(valid),
                   xp.asarray(lens))
    npdt = dtype.np_dtype
    if arr.dtype == object:
        valid_n = np.array([x is not None and x == x for x in arr])
        vals = np.array([x if (x is not None and x == x) else 0
                         for x in arr]).astype(npdt)
    elif np.issubdtype(arr.dtype, np.floating) and \
            not np.issubdtype(npdt, np.floating):
        valid_n = ~np.isnan(arr)
        vals = np.where(valid_n, arr, 0).astype(npdt)
    else:
        valid_n = np.ones(len(arr), bool)
        if np.issubdtype(arr.dtype, np.floating):
            valid_n = ~np.isnan(arr) if not np.issubdtype(npdt, np.floating) \
                else valid_n
        vals = arr.astype(npdt)
    data = np.zeros(cap, npdt)
    valid = np.zeros(cap, bool)
    data[:n] = vals[:n]
    valid[:n] = valid_n[:n]
    return Vec(dtype, xp.asarray(data), xp.asarray(valid))
