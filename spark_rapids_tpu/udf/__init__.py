"""UDF & ML integration layer (reference SURVEY.md §2.8).

Four pieces, mirroring the reference:
  * compiler.py — python-function -> Expression compiler (the role
    `udf-compiler/` plays for Scala bytecode -> Catalyst): a compiled UDF
    becomes an ordinary expression tree, planned and executed on device like
    any built-in.
  * spi.py — TpuUDF SPI (`RapidsUDF.java` analog): users hand-write a
    device-columnar implementation and get device execution.
  * pandas_udf.py — Arrow-based pandas UDFs (`GpuArrowEvalPythonExec.scala`
    analog): host round trip with a batch queue; the worker pool limit plays
    the PythonWorkerSemaphore role.
  * columnar_rdd.py — zero-copy DataFrame <-> JAX arrays handoff
    (`ColumnarRdd.scala:42` / ML-integration analog).
"""

from .compiler import UdfCompileError, compile_udf, python_udf_to_expr  # noqa: F401
from .spi import TpuUDF, ColumnarUDFExpr  # noqa: F401
from .pandas_udf import PandasUDF, pandas_udf  # noqa: F401
from .columnar_rdd import to_jax, from_jax  # noqa: F401
