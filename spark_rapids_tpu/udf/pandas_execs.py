"""Python-UDF exec variants over the Arrow bridge (round-3 verdict #7).

Reference counterparts (~4k LoC across `GpuMapInPandasExec.scala`,
`GpuFlatMapGroupsInPandasExec.scala`, `GpuAggregateInPandasExec.scala`,
`GpuWindowInPandasExecBase.scala`, `GpuFlatMapCoGroupsInPandasExec.scala`,
`python/rapids/daemon.py`): the reference moves GPU batches over Arrow IPC
into forked python workers and back, with PythonWorkerSemaphore bounding
worker concurrency. This framework is already python, so the "worker hop"
is the device->host Arrow boundary around the user function, bounded by
the same PythonWorkerSemaphore; the device side does everything around it
(scan, projection, padding, downstream ops).

Five variants, each a CPU plan node (independent oracle path, pandas
mechanics) + a TPU exec (device batches -> Arrow -> pandas -> device):

  * MapInPandas         fn(iter[pd.DataFrame]) -> iter[pd.DataFrame];
                        input re-chunked to batchSizeRows so the UDF sees
                        the same roundoff the reference's
                        maxRecordsPerBatch produces
  * FlatMapGroupsInPandas (applyInPandas) fn(group_df) -> df per group
  * AggregateInPandas   fn(*series) -> scalar, one output row per group
  * WindowInPandas      fn(*series) -> scalar broadcast over its
                        UNBOUNDED partition frame (the common
                        windowInPandas shape)
  * CoGroupsInPandas    fn(left_df, right_df) -> df per key co-group

Group iteration is key-sorted on BOTH engines — Spark leaves group order
unspecified, so the deterministic order is a free choice that makes the
differential harness exact."""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Sequence, Tuple

import numpy as np

from .. import types as T
from ..columnar.batch import Schema
from ..plan.nodes import PhysicalPlan
from .pandas_udf import PythonWorkerSemaphore

__all__ = [
    "CpuMapInPandasExec", "TpuMapInPandasExec",
    "CpuFlatMapGroupsInPandasExec", "TpuFlatMapGroupsInPandasExec",
    "CpuAggregateInPandasExec", "TpuAggregateInPandasExec",
    "CpuWindowInPandasExec", "TpuWindowInPandasExec",
    "CpuCoGroupsInPandasExec", "TpuCoGroupsInPandasExec",
    "PandasAgg",
]


class PandasAgg:
    """One named pandas aggregation: fn(*pd.Series) -> scalar."""

    def __init__(self, name: str, fn: Callable, return_type: T.DataType,
                 arg_cols: Sequence[str]):
        self.name = name
        self.fn = fn
        self.return_type = return_type
        self.arg_cols = list(arg_cols)


# ----------------------------------------------------------------------------
# Shared host mechanics
# ----------------------------------------------------------------------------

def _hb_to_pandas(hb):
    from ..cpu.hostbatch import host_batch_to_arrow
    return host_batch_to_arrow(hb).to_pandas()


def _pandas_to_hb(df, schema: Schema):
    import pyarrow as pa
    from ..cpu.hostbatch import host_batch_from_arrow
    table = pa.Table.from_pandas(df, schema=schema.to_arrow(),
                                 preserve_index=False)
    return host_batch_from_arrow(table)


def _pandas_to_device(df, schema: Schema):
    import pyarrow as pa
    from ..columnar.batch import batch_from_arrow
    table = pa.Table.from_pandas(df, schema=schema.to_arrow(),
                                 preserve_index=False)
    return batch_from_arrow(table), table.num_rows


def _device_to_pandas(batch):
    from ..columnar.batch import batch_to_arrow
    return batch_to_arrow(batch).to_pandas()


def _chunks(df, max_rows: int):
    if len(df) <= max_rows:
        yield df
        return
    for lo in range(0, len(df), max_rows):
        yield df.iloc[lo:lo + max_rows]


def _permit_per_step(make_it, sem):
    """Advance a user-fn iterable one step per semaphore permit. The
    permit is NEVER held across a yield to the consumer (a generator
    advanced on one thread and closed on another must not strand a
    permit), and each step's acquire/release pair runs on one thread, so
    the semaphore's per-thread reentrancy is sound for nested execs.
    `make_it` is a thunk: an EAGER fn (one returning a list rather than a
    generator) does all its work inside the first permit."""
    it = None
    while True:
        with sem:
            if it is None:
                it = iter(make_it())
            try:
                out = next(it)
            except StopIteration:
                return
        yield out


def _sorted_groups(df, keys: List[str]):
    """Yield (key_df_one_row, group_df) in key-sorted order (deterministic
    on both engines; Spark does not pin an order)."""
    grouped = df.groupby(keys, sort=True, dropna=False)
    for _, g in grouped:
        yield g


def _check_output_columns(df, schema: Schema, what: str):
    missing = [c for c in schema.names if c not in df.columns]
    if missing:
        raise ValueError(f"{what} result is missing declared output "
                         f"columns {missing}")
    return df[list(schema.names)]


# ----------------------------------------------------------------------------
# mapInPandas
# ----------------------------------------------------------------------------

class CpuMapInPandasExec(PhysicalPlan):
    """fn(iterator of pd.DataFrame) -> iterator of pd.DataFrame
    (`GpuMapInPandasExec.scala:1`; output row count is unconstrained)."""

    def __init__(self, fn: Callable, schema: Schema, child: PhysicalPlan,
                 conf=None):
        super().__init__([child])
        self.fn = fn
        self._schema = schema
        self._conf = conf

    @property
    def output(self) -> Schema:
        return self._schema

    def _input_frames(self, max_rows: int):
        for hb in self.children[0].execute_cpu():
            yield from _chunks(_hb_to_pandas(hb), max_rows)

    def execute_cpu(self):
        from ..config import get_default_conf
        conf = self._conf or get_default_conf()
        max_rows = conf.get("spark.rapids.sql.batchSizeRows")
        for out in _permit_per_step(
                lambda: self.fn(self._input_frames(max_rows)),
                PythonWorkerSemaphore.get()):
            if len(out):
                yield _pandas_to_hb(
                    _check_output_columns(out, self._schema,
                                          "mapInPandas"), self._schema)

    def _arg_string(self):
        return f"[{getattr(self.fn, '__name__', '<fn>')}]"


from ..exec.base import TpuExec as _TpuExec  # noqa: E402


class TpuMapInPandasExec(_TpuExec):
    """Device batches stream to host Arrow, through the user iterator fn,
    and back to device — the python-worker hop of the reference with the
    IPC pipe collapsed to the D2H/H2D boundary."""

    def __init__(self, plan: CpuMapInPandasExec, child, conf):
        super().__init__([child], conf)
        self.fn = plan.fn
        self._schema = plan.output

    @property
    def output(self) -> Schema:
        return self._schema

    def _input_frames(self):
        max_rows = self.conf.get("spark.rapids.sql.batchSizeRows")
        for batch in self.children[0].execute():
            yield from _chunks(_device_to_pandas(batch), max_rows)

    def do_execute(self):
        sem = PythonWorkerSemaphore.get(
            self.conf.get("spark.rapids.sql.concurrentGpuTasks"))
        for out in _permit_per_step(
                lambda: self.fn(self._input_frames()), sem):
            if not len(out):
                continue
            b, nrows = _pandas_to_device(
                _check_output_columns(out, self._schema,
                                      "mapInPandas"), self._schema)
            self.num_output_rows.add(nrows)
            yield self._count_output(b)


# ----------------------------------------------------------------------------
# flatMapGroupsInPandas (applyInPandas)
# ----------------------------------------------------------------------------

class CpuFlatMapGroupsInPandasExec(PhysicalPlan):
    """fn(one group's pd.DataFrame) -> pd.DataFrame
    (`GpuFlatMapGroupsInPandasExec.scala:1`). The whole child input is
    materialized to group (same as the reference's requirement that a
    group fits in one batch)."""

    def __init__(self, keys: Sequence[str], fn: Callable, schema: Schema,
                 child: PhysicalPlan):
        super().__init__([child])
        self.keys = list(keys)
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute_cpu(self):
        import pandas as pd
        frames = [_hb_to_pandas(hb)
                  for hb in self.children[0].execute_cpu()]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        sem = PythonWorkerSemaphore.get()
        for g in _sorted_groups(df, self.keys):
            with sem:  # not held across the yield below
                out = self.fn(g.reset_index(drop=True))
            if len(out):
                yield _pandas_to_hb(
                    _check_output_columns(out, self._schema,
                                          "applyInPandas"),
                    self._schema)

    def _arg_string(self):
        return f"[{self.keys}, {getattr(self.fn, '__name__', '<fn>')}]"


class TpuFlatMapGroupsInPandasExec(_TpuExec):
    def __init__(self, plan: CpuFlatMapGroupsInPandasExec, child, conf):
        super().__init__([child], conf)
        self.keys = plan.keys
        self.fn = plan.fn
        self._schema = plan.output

    @property
    def output(self) -> Schema:
        return self._schema

    def do_execute(self):
        import pandas as pd
        frames = [_device_to_pandas(b) for b in self.children[0].execute()]
        frames = [f for f in frames if len(f)]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        with PythonWorkerSemaphore.get(
                self.conf.get("spark.rapids.sql.concurrentGpuTasks")):
            outs = []
            for g in _sorted_groups(df, self.keys):
                out = self.fn(g.reset_index(drop=True))
                if len(out):
                    outs.append(_check_output_columns(
                        out, self._schema, "applyInPandas"))
        if not outs:
            return
        # one H2D per input batch worth of results, not one per group
        b, nrows = _pandas_to_device(
            pd.concat(outs, ignore_index=True), self._schema)
        self.num_output_rows.add(nrows)
        yield self._count_output(b)


# ----------------------------------------------------------------------------
# aggregateInPandas
# ----------------------------------------------------------------------------

def _agg_output_schema(keys: List[str], child_schema: Schema,
                       aggs: Sequence[PandasAgg]) -> Schema:
    names: List[str] = []
    dts: List[T.DataType] = []
    for k in keys:
        names.append(k)
        dts.append(child_schema.types[child_schema.index_of(k)])
    for a in aggs:
        names.append(a.name)
        dts.append(a.return_type)
    return Schema(tuple(names), tuple(dts))


def _run_pandas_aggs(df, keys: List[str], aggs: Sequence[PandasAgg],
                     schema: Schema):
    """Shared grouped-agg mechanics: one output row per key group."""
    import pandas as pd
    rows: Dict[str, list] = {n: [] for n in schema.names}
    for g in _sorted_groups(df, keys):
        for k in keys:
            rows[k].append(g[k].iloc[0])
        for a in aggs:
            rows[a.name].append(a.fn(*[g[c].reset_index(drop=True)
                                       for c in a.arg_cols]))
    return pd.DataFrame(rows, columns=list(schema.names))


class CpuAggregateInPandasExec(PhysicalPlan):
    """Grouped SERIES->SCALAR pandas UDF aggregation
    (`GpuAggregateInPandasExec.scala:1`): output = keys + one value per
    agg per group."""

    def __init__(self, keys: Sequence[str], aggs: Sequence[PandasAgg],
                 child: PhysicalPlan):
        super().__init__([child])
        self.keys = list(keys)
        self.aggs = list(aggs)
        self._schema = _agg_output_schema(self.keys, child.output,
                                          self.aggs)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute_cpu(self):
        import pandas as pd
        frames = [_hb_to_pandas(hb)
                  for hb in self.children[0].execute_cpu()]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        with PythonWorkerSemaphore.get():
            out = _run_pandas_aggs(df, self.keys, self.aggs, self._schema)
        if len(out):
            yield _pandas_to_hb(out, self._schema)

    def _arg_string(self):
        return f"[{self.keys}, {[a.name for a in self.aggs]}]"


class TpuAggregateInPandasExec(_TpuExec):
    def __init__(self, plan: CpuAggregateInPandasExec, child, conf):
        super().__init__([child], conf)
        self.keys = plan.keys
        self.aggs = plan.aggs
        self._schema = plan.output

    @property
    def output(self) -> Schema:
        return self._schema

    def do_execute(self):
        import pandas as pd
        frames = [_device_to_pandas(b) for b in self.children[0].execute()]
        frames = [f for f in frames if len(f)]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        with PythonWorkerSemaphore.get(
                self.conf.get("spark.rapids.sql.concurrentGpuTasks")):
            out = _run_pandas_aggs(df, self.keys, self.aggs, self._schema)
        if not len(out):
            return
        b, nrows = _pandas_to_device(out, self._schema)
        self.num_output_rows.add(nrows)
        yield self._count_output(b)


# ----------------------------------------------------------------------------
# windowInPandas (unbounded partition frame)
# ----------------------------------------------------------------------------

def _window_output_schema(child_schema: Schema,
                          aggs: Sequence[PandasAgg]) -> Schema:
    return Schema(child_schema.names + tuple(a.name for a in aggs),
                  child_schema.types + tuple(a.return_type for a in aggs))


def _run_pandas_window(df, keys: List[str], aggs: Sequence[PandasAgg]):
    """Each agg computes one scalar per partition, broadcast to the
    partition's rows (the UNBOUNDED-to-UNBOUNDED frame windowInPandas
    shape)."""
    for a in aggs:
        if keys:
            vals = df.groupby(keys, sort=False, dropna=False)[
                a.arg_cols].apply(
                lambda g, a=a: a.fn(*[g[c].reset_index(drop=True)
                                      for c in a.arg_cols]))
            merged = df[keys].merge(vals.rename(a.name), left_on=keys,
                                    right_index=True, how="left")
            df[a.name] = merged[a.name].to_numpy()
        else:
            df[a.name] = a.fn(*[df[c].reset_index(drop=True)
                                for c in a.arg_cols])
    return df


class CpuWindowInPandasExec(PhysicalPlan):
    """`GpuWindowInPandasExecBase.scala:1`: pandas UDF evaluated once per
    partition, result broadcast over the partition rows; child columns
    pass through."""

    def __init__(self, keys: Sequence[str], aggs: Sequence[PandasAgg],
                 child: PhysicalPlan):
        super().__init__([child])
        self.keys = list(keys)
        self.aggs = list(aggs)
        self._schema = _window_output_schema(child.output, self.aggs)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute_cpu(self):
        import pandas as pd
        frames = [_hb_to_pandas(hb)
                  for hb in self.children[0].execute_cpu()]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        with PythonWorkerSemaphore.get():
            out = _run_pandas_window(df, self.keys, self.aggs)
        if len(out):
            yield _pandas_to_hb(out[list(self._schema.names)],
                                self._schema)

    def _arg_string(self):
        return f"[{self.keys}, {[a.name for a in self.aggs]}]"


class TpuWindowInPandasExec(_TpuExec):
    def __init__(self, plan: CpuWindowInPandasExec, child, conf):
        super().__init__([child], conf)
        self.keys = plan.keys
        self.aggs = plan.aggs
        self._schema = plan.output

    @property
    def output(self) -> Schema:
        return self._schema

    def do_execute(self):
        import pandas as pd
        frames = [_device_to_pandas(b) for b in self.children[0].execute()]
        frames = [f for f in frames if len(f)]
        if not frames:
            return
        df = pd.concat(frames, ignore_index=True)
        with PythonWorkerSemaphore.get(
                self.conf.get("spark.rapids.sql.concurrentGpuTasks")):
            out = _run_pandas_window(df, self.keys, self.aggs)
        if not len(out):
            return
        b, nrows = _pandas_to_device(out[list(self._schema.names)],
                                     self._schema)
        self.num_output_rows.add(nrows)
        yield self._count_output(b)


# ----------------------------------------------------------------------------
# cogrouped applyInPandas
# ----------------------------------------------------------------------------

class CpuCoGroupsInPandasExec(PhysicalPlan):
    """fn(left_group_df, right_group_df) -> pd.DataFrame per co-group over
    the UNION of both sides' key values
    (`GpuFlatMapCoGroupsInPandasExec.scala:1`); a side with no rows for a
    key contributes an empty frame with its full schema."""

    def __init__(self, left_keys: Sequence[str], right_keys: Sequence[str],
                 fn: Callable, schema: Schema, left: PhysicalPlan,
                 right: PhysicalPlan):
        super().__init__([left, right])
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def _cogroups(self, ldf, rdf):
        def canon(key):
            """Null keys group together (Spark grouping semantics): NaN
            never equals NaN, so normalize every missing value to None
            before the two sides' key sets are unioned."""
            def c1(x):
                return None if x is None or x != x else x
            return tuple(c1(x) for x in key) if isinstance(key, tuple) \
                else c1(key)

        lg = {canon(k): g for k, g in ldf.groupby(
            self.left_keys, sort=True, dropna=False)}
        rg = {canon(k): g for k, g in rdf.groupby(
            self.right_keys, sort=True, dropna=False)}
        for key in sorted(set(lg) | set(rg), key=repr):
            lpart = lg.get(key)
            rpart = rg.get(key)
            if lpart is None:
                lpart = ldf.iloc[0:0]
            if rpart is None:
                rpart = rdf.iloc[0:0]
            yield (lpart.reset_index(drop=True),
                   rpart.reset_index(drop=True))

    def execute_cpu(self):
        import pandas as pd
        lf = [_hb_to_pandas(hb) for hb in self.children[0].execute_cpu()]
        rf = [_hb_to_pandas(hb) for hb in self.children[1].execute_cpu()]
        ldf = pd.concat(lf, ignore_index=True) if lf else \
            _empty_frame(self.children[0].output)
        rdf = pd.concat(rf, ignore_index=True) if rf else \
            _empty_frame(self.children[1].output)
        sem = PythonWorkerSemaphore.get()
        for lpart, rpart in self._cogroups(ldf, rdf):
            with sem:  # not held across the yield below
                out = self.fn(lpart, rpart)
            if len(out):
                yield _pandas_to_hb(
                    _check_output_columns(out, self._schema,
                                          "cogrouped applyInPandas"),
                    self._schema)

    def _arg_string(self):
        return f"[{self.left_keys}|{self.right_keys}]"


def _empty_frame(schema: Schema):
    return schema.to_arrow().empty_table().to_pandas()


class TpuCoGroupsInPandasExec(_TpuExec):
    def __init__(self, plan: CpuCoGroupsInPandasExec, left, right, conf):
        super().__init__([left, right], conf)
        self.plan = plan
        self._schema = plan.output

    @property
    def output(self) -> Schema:
        return self._schema

    def do_execute(self):
        import pandas as pd
        lf = [_device_to_pandas(b) for b in self.children[0].execute()]
        rf = [_device_to_pandas(b) for b in self.children[1].execute()]
        lf = [f for f in lf if len(f)]
        rf = [f for f in rf if len(f)]
        ldf = pd.concat(lf, ignore_index=True) if lf else \
            _empty_frame(self.plan.children[0].output)
        rdf = pd.concat(rf, ignore_index=True) if rf else \
            _empty_frame(self.plan.children[1].output)
        outs = []
        with PythonWorkerSemaphore.get(
                self.conf.get("spark.rapids.sql.concurrentGpuTasks")):
            for lpart, rpart in self.plan._cogroups(ldf, rdf):
                out = self.plan.fn(lpart, rpart)
                if len(out):
                    outs.append(_check_output_columns(
                        out, self._schema, "cogrouped applyInPandas"))
        if not outs:
            return
        b, nrows = _pandas_to_device(
            pd.concat(outs, ignore_index=True), self._schema)
        self.num_output_rows.add(nrows)
        yield self._count_output(b)
