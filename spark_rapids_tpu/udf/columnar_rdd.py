"""Zero-copy DataFrame <-> JAX handoff for ML.

Reference: `ColumnarRdd.scala:42` / `InternalColumnarRddConverter.scala` /
`GpuBringBackToHost.scala` export a DataFrame as `RDD[cudf.Table]` so XGBoost
consumes device memory without a host round trip (doc
`docs/additional-functionality/ml-integration.md`). Here the batches already
hold jax arrays in HBM, so the handoff is literally the arrays: `to_jax`
executes the plan on device and returns the device columns (no D2H), and
`from_jax` wraps arrays back into a DataFrame source."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .. import types as T

__all__ = ["to_jax", "from_jax"]


def to_jax(df) -> Dict[str, Tuple]:
    """Execute `df` on the TPU engine and return
    {column: (data, validity[, lengths])} of DEVICE arrays, sliced info kept
    as (arrays, num_rows) — arrays stay padded (capacity) with `num_rows`
    live rows, ready to feed a jax model without leaving HBM."""
    batches = df.session.execute_plan_device_batches(df.plan)
    from ..exec.coalesce import concat_batches
    batch = concat_batches(batches)
    out: Dict[str, Tuple] = {"__num_rows__": int(batch.row_count())}
    for name, col in zip(batch.schema.names, batch.columns):
        if col.lengths is None:
            out[name] = (col.data, col.validity)
        else:
            out[name] = (col.data, col.validity, col.lengths)
    return out


def from_jax(session, arrays: Dict[str, Tuple], num_rows: Optional[int] = None):
    """Wrap device arrays back into a DataFrame (inverse handoff; this
    direction materializes through the host scan source — the export path
    `to_jax` is the zero-copy one). `arrays` maps column name ->
    (data, validity) jax arrays; all leading dims must match. Types are
    inferred from array dtypes."""
    import jax.numpy as jnp
    from ..columnar.batch import ColumnarBatch, Schema
    from ..columnar.column import Column
    items = [(k, v) for k, v in arrays.items() if k != "__num_rows__"]
    if num_rows is None:
        num_rows = arrays.get("__num_rows__")
    if num_rows is None:
        raise ValueError("num_rows required (or a __num_rows__ key)")
    names, tps, cols = [], [], []
    for name, parts in items:
        data, validity = parts[0], parts[1]
        lengths = parts[2] if len(parts) > 2 else None
        if lengths is not None:
            dt = T.STRING
        else:
            dt = _dtype_from_np(np.dtype(data.dtype))
        names.append(name)
        tps.append(dt)
        cols.append(Column(dt, data, validity, lengths))
    batch = ColumnarBatch(Schema(tuple(names), tuple(tps)), tuple(cols),
                          jnp.asarray(num_rows, dtype=jnp.int32))
    return session.from_device_batch(batch)


def _dtype_from_np(npdt: np.dtype) -> T.DataType:
    table = {np.dtype(np.bool_): T.BOOLEAN, np.dtype(np.int8): T.BYTE,
             np.dtype(np.int16): T.SHORT, np.dtype(np.int32): T.INT,
             np.dtype(np.int64): T.LONG, np.dtype(np.float32): T.FLOAT,
             np.dtype(np.float64): T.DOUBLE}
    if npdt not in table:
        raise TypeError(f"no SQL type for array dtype {npdt}")
    return table[npdt]
