"""Columnar UDF SPI.

Reference: `RapidsUDF.java` — users implement `evaluateColumnar(ColumnVector
...)` to get native-speed UDF execution instead of the row-based black box
(`GpuUserDefinedFunction.scala`, doc `docs/additional-functionality/
rapids-udfs.md`). The TPU analog: subclass `TpuUDF` and implement
`evaluate_columnar(xp, *vecs) -> Vec` with array ops — it runs inside the
jitted kernels on device, AND serves as its own CPU differential peer (xp is
numpy on the CPU engine)."""

from __future__ import annotations

from typing import Sequence

from .. import types as T
from ..expr.base import Expression, EvalContext, Vec

__all__ = ["TpuUDF", "ColumnarUDFExpr"]


class TpuUDF:
    """User-implemented columnar UDF: declare the return type and implement
    the computation xp-generically (jnp under jit on device, numpy on the
    CPU engine)."""

    #: the Spark return type of the UDF
    return_type: T.DataType = T.DOUBLE
    #: is the result row-for-row deterministic (affects planning)
    deterministic: bool = True

    def evaluate_columnar(self, xp, *vecs: Vec) -> Vec:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def __call__(self, *args: Expression) -> "ColumnarUDFExpr":
        return ColumnarUDFExpr(self, list(args))


class ColumnarUDFExpr(Expression):
    """Expression node wrapping a TpuUDF (GpuUserDefinedFunction analog)."""

    def __init__(self, udf: TpuUDF, children: Sequence[Expression]):
        super().__init__(list(children))
        self.udf = udf

    @property
    def data_type(self) -> T.DataType:
        return self.udf.return_type

    @property
    def deterministic(self) -> bool:  # type: ignore[override]
        return self.udf.deterministic

    def _compute(self, ctx: EvalContext, *vecs: Vec) -> Vec:
        out = self.udf.evaluate_columnar(ctx.xp, *vecs)
        if not isinstance(out, Vec):
            raise TypeError(
                f"TpuUDF {self.udf.name}.evaluate_columnar must return a Vec")
        return out

    def __repr__(self):
        return f"{self.udf.name}({', '.join(map(repr, self.children))})"
