"""Python UDF -> Expression compiler.

Reference: `udf-compiler/` (2,353 LoC) decompiles simple Scala-UDF JVM
bytecode into Catalyst expressions (`CFG.scala`, `Instruction.scala`,
`CatalystExpressionBuilder.scala`), so the UDF stops being a black box and is
planned/fused like a built-in. The TPU analog works on the Python AST instead
of JVM bytecode — same idea, friendlier source: a restricted subset of Python
(arithmetic, comparisons, boolean logic, conditionals, math calls, string
methods) is translated into this framework's expression IR. Anything outside
the subset raises `UdfCompileError` and the caller falls back to a pandas UDF
(host round trip), exactly like the reference falls back to the row-based
black-box UDF when decompilation fails.

Semantics note: the produced expression has SPARK semantics (e.g. `%` maps to
`pmod`, matching Python's sign rule for positive divisors; integer `/` is
float division in both languages).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, Dict, List, Optional, Sequence

from .. import types as T
from ..expr import arithmetic as EA
from ..expr import conditional as ECO
from ..expr import math_ as EM
from ..expr import predicates as EP
from ..expr import strings as ES
from ..expr.base import Expression, Literal

__all__ = ["UdfCompileError", "python_udf_to_expr", "compile_udf"]


class UdfCompileError(ValueError):
    """The function uses Python outside the compilable subset."""


_BINOPS = {
    ast.Add: EA.Add,
    ast.Sub: EA.Subtract,
    ast.Mult: EA.Multiply,
    ast.Div: EA.Divide,
    ast.FloorDiv: EA.IntegralDivide,
    ast.Mod: EA.Pmod,          # python sign rule == pmod for divisor > 0
    ast.Pow: EM.Pow,
}

_CMPOPS = {
    ast.Eq: EP.EqualTo,
    ast.NotEq: lambda a, b: EP.Not(EP.EqualTo(a, b)),
    ast.Lt: EP.LessThan,
    ast.LtE: EP.LessThanOrEqual,
    ast.Gt: EP.GreaterThan,
    ast.GtE: EP.GreaterThanOrEqual,
}

_MATH_CALLS = {
    "sqrt": EM.Sqrt, "exp": EM.Exp, "log": EM.Log, "log10": EM.Log10,
    "log2": EM.Log2, "sin": EM.Sin, "cos": EM.Cos, "tan": EM.Tan,
    "asin": EM.Asin, "acos": EM.Acos, "atan": EM.Atan, "sinh": EM.Sinh,
    "cosh": EM.Cosh, "tanh": EM.Tanh, "floor": EM.Floor, "ceil": EM.Ceil,
    "degrees": EM.ToDegrees, "radians": EM.ToRadians,
}

_STR_METHODS = {
    "upper": ES.Upper, "lower": ES.Lower, "strip": ES.StringTrim,
    "lstrip": ES.StringTrimLeft, "rstrip": ES.StringTrimRight,
}

_STR_METHODS_2 = {
    "startswith": ES.StartsWith, "endswith": ES.EndsWith,
}


class _Translator:
    def __init__(self, env: Dict[str, Expression], fn_name: str):
        self.env = dict(env)
        self.fn_name = fn_name

    def fail(self, node, msg: str):
        raise UdfCompileError(
            f"udf {self.fn_name}: line {getattr(node, 'lineno', '?')}: {msg}")

    # -- statements ---------------------------------------------------------
    def run_body(self, body: List[ast.stmt]) -> Expression:
        """Translate a statement list to the expression it returns. Supports
        straight-line assignments and fully-returning if/elif/else trees (the
        CFG shapes the reference's bytecode decompiler accepts)."""
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.Return):
                if stmt.value is None:
                    self.fail(stmt, "bare return (must return a value)")
                return self.expr(stmt.value)
            if isinstance(stmt, ast.Assign):
                if len(stmt.targets) != 1 or \
                        not isinstance(stmt.targets[0], ast.Name):
                    self.fail(stmt, "only simple single-name assignment")
                self.env[stmt.targets[0].id] = self.expr(stmt.value)
                continue
            if isinstance(stmt, ast.AugAssign):
                if not isinstance(stmt.target, ast.Name):
                    self.fail(stmt, "only simple augmented assignment")
                name = stmt.target.id
                if name not in self.env:
                    self.fail(stmt, f"augmented assign to unbound {name!r}")
                cls = _BINOPS.get(type(stmt.op))
                if cls is None:
                    self.fail(stmt, "unsupported augmented operator")
                self.env[name] = cls(self.env[name], self.expr(stmt.value))
                continue
            if isinstance(stmt, ast.Import):
                if all(a.name == "math" and a.asname is None
                       for a in stmt.names):
                    continue  # `import math` inside the body is fine
                self.fail(stmt, "only `import math` is allowed in a udf")
            if isinstance(stmt, ast.If):
                cond = self.expr(stmt.test)
                # the else path is the explicit orelse plus the fallthrough
                # continuation (unreachable statements after a returning else
                # are harmless)
                else_body = stmt.orelse + body[i + 1:]
                if not else_body:
                    self.fail(stmt, "if-branch with no else and no "
                                    "following statements")
                if _always_returns(stmt.body):
                    then_t = _Translator(self.env, self.fn_name)
                    then_e = then_t.run_body(stmt.body)
                    else_t = _Translator(self.env, self.fn_name)
                    else_e = else_t.run_body(else_body)
                    return ECO.If(cond, then_e, else_e)
                self.fail(stmt, "if-branches must return (no fallthrough "
                                "merges; restructure as expressions)")
            self.fail(stmt, f"unsupported statement {type(stmt).__name__}")
        self.fail(body[-1] if body else ast.Pass(),
                  "function body never returns")

    # -- expressions --------------------------------------------------------
    def expr(self, node: ast.expr) -> Expression:
        if isinstance(node, ast.Constant):
            if node.value is None or isinstance(node.value,
                                                (bool, int, float, str)):
                return Literal(node.value)
            self.fail(node, f"unsupported constant {node.value!r}")
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            self.fail(node, f"unbound name {node.id!r}")
        if isinstance(node, ast.BinOp):
            cls = _BINOPS.get(type(node.op))
            if cls is None:
                self.fail(node, f"operator {type(node.op).__name__}")
            left, right = self.expr(node.left), self.expr(node.right)
            if isinstance(node.op, ast.Add) and _is_stringy(left, right):
                return ES.Concat(left, right)
            return cls(left, right)
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.USub):
                return EA.UnaryMinus(self.expr(node.operand))
            if isinstance(node.op, ast.Not):
                return EP.Not(self.expr(node.operand))
            self.fail(node, f"unary {type(node.op).__name__}")
        if isinstance(node, ast.BoolOp):
            cls = EP.And if isinstance(node.op, ast.And) else EP.Or
            out = self.expr(node.values[0])
            for v in node.values[1:]:
                out = cls(out, self.expr(v))
            return out
        if isinstance(node, ast.Compare):
            parts = []
            left = self.expr(node.left)
            for op, comp in zip(node.ops, node.comparators):
                right = self.expr(comp)
                if isinstance(op, (ast.In, ast.NotIn)):
                    # needle must not be an obviously-non-string literal;
                    # unresolved column types defer to binding-time checks
                    if isinstance(node.left, ast.Constant) and \
                            not isinstance(node.left.value, str):
                        self.fail(node, "`in` only supported for strings")
                    e = ES.Contains(right, left)  # 'x' in s => Contains(s,x)
                    parts.append(EP.Not(e) if isinstance(op, ast.NotIn)
                                 else e)
                else:
                    cls = _CMPOPS.get(type(op))
                    if cls is None:
                        self.fail(node, f"comparison {type(op).__name__}")
                    parts.append(cls(left, right))
                left = right
            out = parts[0]
            for p in parts[1:]:
                out = EP.And(out, p)
            return out
        if isinstance(node, ast.IfExp):
            return ECO.If(self.expr(node.test), self.expr(node.body),
                          self.expr(node.orelse))
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.Subscript):
            self.fail(node, "subscripts are not compilable")
        self.fail(node, f"unsupported expression {type(node).__name__}")

    def call(self, node: ast.Call) -> Expression:
        if node.keywords:
            self.fail(node, "keyword arguments are not compilable")
        args = [self.expr(a) for a in node.args]
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name == "abs" and len(args) == 1:
                return EA.Abs(args[0])
            if name == "len" and len(args) == 1:
                return ES.Length(args[0])
            if name == "min" and len(args) >= 2:
                return ECO.Least(*args)
            if name == "max" and len(args) >= 2:
                return ECO.Greatest(*args)
            if name == "round" and len(args) in (1, 2):
                if len(args) == 1:
                    return EM.Round(args[0], 0)
                sc = node.args[1]
                if not (isinstance(sc, ast.Constant)
                        and isinstance(sc.value, int)):
                    self.fail(node, "round() scale must be an int literal")
                return EM.Round(args[0], sc.value)
            if name == "float" and len(args) == 1:
                from ..expr.cast import Cast
                return Cast(args[0], T.DOUBLE)
            if name == "int" and len(args) == 1:
                from ..expr.cast import Cast
                return Cast(args[0], T.LONG)
            if name == "str" and len(args) == 1:
                from ..expr.cast import Cast
                return Cast(args[0], T.STRING)
            self.fail(node, f"call to {name!r} is not compilable")
        if isinstance(f, ast.Attribute):
            # math.xxx(arg) or string_expr.method(...)
            if isinstance(f.value, ast.Name) and f.value.id == "math":
                cls = _MATH_CALLS.get(f.attr)
                if cls is not None and len(args) == 1:
                    return cls(args[0])
                if f.attr == "pow" and len(args) == 2:
                    return EM.Pow(*args)
                self.fail(node, f"math.{f.attr} is not compilable")
            recv = self.expr(f.value)
            if f.attr in _STR_METHODS and not args:
                return _STR_METHODS[f.attr](recv)
            if f.attr in _STR_METHODS_2 and len(args) == 1:
                return _STR_METHODS_2[f.attr](recv, args[0])
            self.fail(node, f"method .{f.attr}() is not compilable")
        self.fail(node, "unsupported call form")


def _always_returns(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Return):
            return True
        if isinstance(stmt, ast.If) and stmt.orelse and \
                _always_returns(stmt.body) and _always_returns(stmt.orelse):
            return True
    return False


def _is_stringy(*exprs: Expression) -> bool:
    for e in exprs:
        try:
            if isinstance(e.data_type, T.StringType):
                return True
        except Exception:
            pass
    return False


def python_udf_to_expr(fn: Callable,
                       args: Sequence[Expression]) -> Expression:
    """Compile fn(*args) into an expression tree, or raise UdfCompileError."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise UdfCompileError(f"cannot get source of {fn!r}: {e}")
    tree = ast.parse(src)
    fdefs = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
    if isinstance(tree.body[0], ast.FunctionDef):
        fdef = tree.body[0]
    elif fdefs:
        fdef = fdefs[0]
    else:
        # lambda source: grab the Lambda node
        lambdas = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
        if not lambdas:
            raise UdfCompileError(f"no function definition found in {src!r}")
        lam = lambdas[0]
        params = [a.arg for a in lam.args.args]
        if len(params) != len(args):
            raise UdfCompileError(
                f"lambda takes {len(params)} args, given {len(args)}")
        tr = _Translator(dict(zip(params, args)), "<lambda>")
        return tr.expr(lam.body)
    params = [a.arg for a in fdef.args.args]
    if fdef.args.vararg or fdef.args.kwarg or fdef.args.kwonlyargs:
        raise UdfCompileError("*args/**kwargs are not compilable")
    if len(params) != len(args):
        raise UdfCompileError(
            f"{fn.__name__} takes {len(params)} args, given {len(args)}")
    tr = _Translator(dict(zip(params, args)), fdef.name)
    return tr.run_body(fdef.body)


def compile_udf(fn: Callable):
    """Decorator: use as `@compile_udf`; calling the result with column
    expressions yields the compiled expression tree (or raises). The
    uncompiled python function stays available as `.fn` for the pandas
    fallback path."""

    @functools.wraps(fn)
    def wrapper(*args: Expression) -> Expression:
        return python_udf_to_expr(fn, args)

    wrapper.fn = fn
    return wrapper
