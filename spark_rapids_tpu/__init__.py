"""spark_rapids_tpu — TPU-native accelerator with the capabilities of the RAPIDS
Accelerator for Apache Spark (see ARCHITECTURE.md / SURVEY.md)."""

__version__ = "0.1.0"

import jax as _jax

# LONG/DOUBLE are core SQL types; the framework is unusable with 32-bit-only math.
# (On TPU, f64 lowers to XLA's emulation; the planner can demote DOUBLE compute to f32
# when spark.rapids.tpu.f64.emulation=false.)
_jax.config.update("jax_enable_x64", True)

from . import types  # noqa: F401
from .config import TpuConf, get_default_conf  # noqa: F401
