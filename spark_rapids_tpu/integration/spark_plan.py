"""Spark physical-plan adapter — the integration seam with a REAL Spark
session (reference: the plugin intercepts executed plans inside the JVM,
`Plugin.scala:222`, `GpuOverrides.scala:4239-4266`).

This engine is standalone, so the seam is serialized plans: Spark's
`df.queryExecution.executedPlan.toJSON` (TreeNode.toJSON — a stable,
versioned facility of Catalyst: pre-order node array, each node carrying
`class`, `num-children`, and its fields, with expression trees nested in
the same shape) translates into `plan/nodes.py` and runs through the
override rewrite like any native plan.

HONEST GAP: this image has no pyspark/JVM, so there is no live Py4J or
Spark Connect listener here — the adapter is exercised against committed
toJSON fixtures (tests/fixtures/spark_plans/) whose shape follows the
TreeNode.toJSON contract. Wiring it to a live session is a transport
concern (ship the JSON over any channel); the translation below is the
load-bearing part.

Supported nodes: FileSourceScanExec (parquet), ProjectExec, FilterExec,
HashAggregateExec (partial/final pairs collapse into one engine
aggregate), SortMergeJoin/ShuffledHashJoin/BroadcastHashJoinExec,
BroadcastNestedLoopJoinExec, CartesianProductExec, SortExec,
TakeOrderedAndProjectExec, *LimitExec, UnionExec, RangeExec, ExpandExec,
GenerateExec (explode/posexplode +outer), WindowExec (rank family,
lead/lag, nth_value, framed aggregates), DataWritingCommandExec
(InsertIntoHadoopFsRelationCommand -> write exec), ShuffleExchangeExec /
AdaptiveSparkPlan / WholeStageCodegen / InputAdapter / ReusedExchange
(transparent). Unknown nodes raise UnsupportedSparkPlan with the class
name, mirroring the reference's explain-style honesty."""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..plan import nodes as N

__all__ = ["translate_spark_plan", "UnsupportedSparkPlan"]


class UnsupportedSparkPlan(Exception):
    pass


def _cls(name: str) -> str:
    return name.rsplit(".", 1)[-1]


# ---------------------------------------------------------------------------
# tree reconstruction: toJSON is a PRE-ORDER array with num-children links
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("cls", "fields", "children")

    def __init__(self, cls: str, fields: dict):
        self.cls = cls
        self.fields = fields
        self.children: List["_Node"] = []


def _build_tree(arr: List[dict]) -> _Node:
    pos = [0]

    def rec() -> _Node:
        raw = arr[pos[0]]
        pos[0] += 1
        node = _Node(_cls(raw["class"]), raw)
        for _ in range(int(raw.get("num-children", 0))):
            node.children.append(rec())
        return node

    root = rec()
    return root


def _expr_tree(v) -> Optional[_Node]:
    """Expression fields hold a nested toJSON array (often wrapped in an
    extra list level)."""
    if v is None:
        return None
    if isinstance(v, list):
        if not v:
            return None
        if isinstance(v[0], dict):
            return _build_tree(v)
        return _expr_tree(v[0])
    return None


def _expr_list(v) -> List[_Node]:
    """A field holding a LIST of expression trees."""
    if not isinstance(v, list):
        return []
    out = []
    for item in v:
        t = _expr_tree(item if isinstance(item, list) else [item])
        if t is not None:
            out.append(t)
    return out


# ---------------------------------------------------------------------------
# type + expression translation
# ---------------------------------------------------------------------------

_TYPES = {
    "boolean": T.BOOLEAN, "byte": T.BYTE, "short": T.SHORT,
    "integer": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "null": T.NULL,
}

_DEC_RE = re.compile(r"decimal\((\d+),(\d+)\)")


def _data_type(s) -> T.DataType:
    if isinstance(s, dict):  # nested types serialize as json objects
        kind = s.get("type")
        if kind == "array":
            return T.ArrayType(_data_type(s.get("elementType")),
                               bool(s.get("containsNull", True)))
        if kind == "struct":
            return T.StructType(tuple(
                T.StructField(f["name"], _data_type(f["type"]),
                              bool(f.get("nullable", True)))
                for f in s.get("fields", [])))
        if kind == "map":
            return T.MapType(_data_type(s.get("keyType")),
                             _data_type(s.get("valueType")),
                             bool(s.get("valueContainsNull", True)))
        raise UnsupportedSparkPlan(f"dataType {s}")
    m = _DEC_RE.match(str(s))
    if m:
        return T.DecimalType(int(m.group(1)), int(m.group(2)))
    dt = _TYPES.get(str(s))
    if dt is None:
        raise UnsupportedSparkPlan(f"dataType {s}")
    return dt


def _literal_value(node: _Node):
    v = node.fields.get("value")
    dt = _data_type(node.fields.get("dataType"))
    if v is None:  # JSON null IS the null literal; the STRING "null" is
        return None, dt  # a genuine four-character payload
    if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.LongType)):
        return int(v), dt
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return float(v), dt
    if isinstance(dt, T.BooleanType):
        return str(v).lower() == "true", dt
    if isinstance(dt, T.DecimalType):
        import decimal
        return decimal.Decimal(str(v)), dt
    if isinstance(dt, (T.DateType, T.TimestampType)):
        # Catalyst serializes the INTERNAL value (days / micros since
        # epoch); accept an ISO string too for hand-written fixtures
        s = str(v)
        try:
            return int(s), dt
        except ValueError:
            pass
        import datetime
        if isinstance(dt, T.DateType):
            return (datetime.date.fromisoformat(s) -
                    datetime.date(1970, 1, 1)).days, dt
        d = datetime.datetime.fromisoformat(s)
        if d.tzinfo is None:
            d = d.replace(tzinfo=datetime.timezone.utc)
        return int(d.timestamp() * 1_000_000), dt
    return str(v), dt


# -- generic expression registry ---------------------------------------------
# The engine's expression classes deliberately carry Catalyst's names with
# children in Catalyst's order, so MOST of the surface translates
# generically: EngineClass(*translated_children). The registry below maps
# name -> class from the expr modules; classes whose constructors take
# literal python parameters (fmt: str, scale: int, ...) are handled by the
# _SPECIAL builders and EXCLUDED from the generic path (a signature sweep
# refuses anything with a non-Expression parameter rather than construct
# garbage). Reference surface: GpuOverrides.scala:866-3475.

_EXPR_MODULES = (
    "arithmetic", "bitwise", "collections", "collections_ext",
    "conditional", "datetime_", "hashing", "hashing_ext", "json_",
    "maps", "math_", "misc", "nullexprs", "predicates", "regex",
    "splits", "strings", "strings_ext", "strings_more",
)

# Catalyst physical class name -> engine class name where they differ
# (None = explicitly unsupported); classes also in _SPECIAL don't belong
# here — the special builders are consulted first
_CATALYST_ALIASES = {
    "EulerNumber": "Euler",
    "Rand": None,  # non-deterministic: explicitly unsupported
}

# Catalyst wrapper nodes that are semantic no-ops for this engine: the
# decimal type arithmetic promotes exactly (256-bit limbs), floats are
# already IEEE-normalized on device
_PASSTHROUGH = {"PromotePrecision", "KnownNotNull", "KnownNonNullable",
                "NormalizeNaNAndZero", "KnownFloatingPointNormalized"}


def _engine_expr_classes() -> Dict[str, type]:
    global _EXPR_REGISTRY
    if _EXPR_REGISTRY is not None:
        return _EXPR_REGISTRY
    import importlib
    from ..expr.base import Expression
    reg: Dict[str, type] = {}
    for m in _EXPR_MODULES:
        mod = importlib.import_module(f"spark_rapids_tpu.expr.{m}")
        for nm in dir(mod):
            obj = getattr(mod, nm)
            if isinstance(obj, type) and issubclass(obj, Expression) \
                    and obj.__module__ == mod.__name__:
                reg.setdefault(nm, obj)
    _EXPR_REGISTRY = reg
    return reg


_EXPR_REGISTRY: Optional[Dict[str, type]] = None
_GENERIC_OK_CACHE: Dict[str, bool] = {}


def _generic_applicable(name: str, cls: type) -> bool:
    """True when every constructor parameter is Expression-shaped (safe to
    feed translated children positionally)."""
    ok = _GENERIC_OK_CACHE.get(name)
    if ok is not None:
        return ok
    import inspect
    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        _GENERIC_OK_CACHE[name] = False
        return False
    ok = True
    for p in list(sig.parameters.values())[1:]:  # skip self
        ann = str(p.annotation)
        if p.kind == p.VAR_POSITIONAL:
            continue
        if p.annotation is inspect.Parameter.empty or "Expression" in ann:
            continue
        ok = False
        break
    _GENERIC_OK_CACHE[name] = ok
    return ok


def _lit(node: _Node):
    """Require a Literal child and return its python value."""
    if node.cls != "Literal":
        raise UnsupportedSparkPlan(f"non-literal argument {node.cls}")
    v, _ = _literal_value(node)
    return v


def _tx(node: _Node):
    return _translate_expr(node)


def _in_set(node: _Node):
    """InSet serializes the value set in the `hset` field as raw values
    typed by the child expression."""
    from ..expr import predicates as EP
    value = _tx(node.children[0])
    dt = _data_type(node.children[0].fields.get("dataType")) \
        if node.children[0].fields.get("dataType") else None
    hs = node.fields.get("hset")
    if not isinstance(hs, list):
        raise UnsupportedSparkPlan("InSet without hset")
    items = []
    for v in hs:  # In takes raw python values, typed by the child
        if v is None or dt is None:
            items.append(v)
        elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType,
                             T.LongType, T.DateType, T.TimestampType)):
            items.append(int(v))  # date/timestamp hsets hold internal ints
        elif isinstance(dt, (T.FloatType, T.DoubleType)):
            items.append(float(v))
        elif isinstance(dt, T.BooleanType):
            items.append(v if isinstance(v, bool)
                         else str(v).lower() == "true")
        elif isinstance(dt, T.DecimalType):
            import decimal
            items.append(decimal.Decimal(str(v)))
        elif isinstance(dt, T.StringType):
            items.append(str(v))
        else:
            raise UnsupportedSparkPlan(f"InSet over {dt}")
    return EP.In(value, items)


def _case_when(kids: List[_Node]):
    """CaseWhen children: (cond, value)* + optional else."""
    from ..expr.conditional import CaseWhen
    pairs = [(_tx(kids[i]), _tx(kids[i + 1]))
             for i in range(0, len(kids) - len(kids) % 2, 2)]
    else_e = _tx(kids[-1]) if len(kids) % 2 else None
    return CaseWhen(pairs, else_e)


def _named_struct(kids: List[_Node]):
    from ..expr.collections import CreateNamedStruct
    names = [str(_lit(kids[i])) for i in range(0, len(kids), 2)]
    values = [_tx(kids[i]) for i in range(1, len(kids), 2)]
    return CreateNamedStruct(names, values)


def _special_builders():
    """Catalyst class name -> builder(children, fields). Covers the
    classes whose engine constructors take literal python parameters (or
    whose Catalyst serialization needs field access)."""
    global _SPECIAL
    if _SPECIAL is not None:
        return _SPECIAL
    from ..expr import predicates as EP
    from ..expr import (collections as CO, datetime_ as DT, hashing as HA,
                        hashing_ext as HX, maps as MP, math_ as MA,
                        regex as RX, splits as SP)

    _SPECIAL = {
        # In's item list is raw python values in the engine
        "In": lambda k, f: EP.In(_tx(k[0]), [_lit(x) for x in k[1:]]),
        # InSet needs the whole node (hset field) — handled before the
        # special lookup in _translate_expr
        "CaseWhen": lambda k, f: _case_when(k),
        "CreateNamedStruct": lambda k, f: _named_struct(k),
        "CreateArray": lambda k, f: CO.CreateArray([_tx(x) for x in k]),
        "CreateMap": lambda k, f: MP.CreateMap([_tx(x) for x in k]),
        "GetStructField": lambda k, f: CO.GetStructField(
            _tx(k[0]), ordinal=f.get("ordinal"), name=f.get("name")),
        "Round": lambda k, f: MA.Round(_tx(k[0]), int(_lit(k[1]))),
        "BRound": lambda k, f: MA.BRound(_tx(k[0]), int(_lit(k[1]))),
        "Sha2": lambda k, f: HX.Sha2(_tx(k[0]), int(_lit(k[1]))),
        "Like": lambda k, f: RX.Like(_tx(k[0]), _tx(k[1]),
                                     str(f.get("escapeChar", "\\"))),
        "RegExpExtract": lambda k, f: RX.RegExpExtract(
            _tx(k[0]), _tx(k[1]), int(_lit(k[2])) if len(k) > 2 else 1),
        "RegExpExtractAll": lambda k, f: RX.RegExpExtractAll(
            _tx(k[0]), _tx(k[1]), int(_lit(k[2])) if len(k) > 2 else 1),
        "StringSplit": lambda k, f: SP.StringSplit(
            _tx(k[0]), str(_lit(k[1])),
            int(_lit(k[2])) if len(k) > 2 else -1),
        "StringToMap": lambda k, f: MP.StringToMap(
            _tx(k[0]),
            str(_lit(k[1])) if len(k) > 1 else ",",
            str(_lit(k[2])) if len(k) > 2 else ":"),
        "SortArray": lambda k, f: CO.SortArray(
            _tx(k[0]), bool(_lit(k[1])) if len(k) > 1 else True),
        "UnixTimestamp": lambda k, f: DT.UnixTimestamp(
            _tx(k[0]), str(_lit(k[1])) if len(k) > 1
            else "yyyy-MM-dd HH:mm:ss"),
        "ToUnixTimestamp": lambda k, f: DT.ToUnixTimestamp(
            _tx(k[0]), str(_lit(k[1])) if len(k) > 1
            else "yyyy-MM-dd HH:mm:ss"),
        "FromUnixTime": lambda k, f: DT.FromUnixTime(
            _tx(k[0]), str(_lit(k[1])) if len(k) > 1
            else "yyyy-MM-dd HH:mm:ss"),
        "DateFormatClass": lambda k, f: DT.DateFormat(_tx(k[0]),
                                                      str(_lit(k[1]))),
        "TruncDate": lambda k, f: DT.TruncDate(_tx(k[0]),
                                               str(_lit(k[1]))),
        "TruncTimestamp": lambda k, f: DT.TruncTimestamp(
            str(_lit(k[0])), _tx(k[1])),
        "NextDay": lambda k, f: DT.NextDay(_tx(k[0]), str(_lit(k[1]))),
        "MonthsBetween": lambda k, f: DT.MonthsBetween(
            _tx(k[0]), _tx(k[1]),
            bool(_lit(k[2])) if len(k) > 2 else True),
        "Murmur3Hash": lambda k, f: HA.Murmur3Hash(
            *[_tx(x) for x in k], seed=int(f.get("seed", 42))),
        "HiveHash": lambda k, f: HA.HiveHash(*[_tx(x) for x in k]),
        "XxHash64": lambda k, f: HX.XxHash64(
            *[_tx(x) for x in k], seed=int(f.get("seed", 42))),
    }
    return _SPECIAL


_SPECIAL: Optional[dict] = None


def _translate_expr(node: _Node):
    from ..expr import base as EB
    from ..expr import cast as EC
    c = node.cls
    kids = node.children
    if c == "AttributeReference":
        return EB.AttributeReference(node.fields["name"],
                                     _data_type(node.fields["dataType"]))
    if c == "Literal":
        v, dt = _literal_value(node)
        return EB.Literal(v, dt)
    if c == "Alias":
        return EB.Alias(_translate_expr(kids[0]), node.fields["name"])
    if c in ("Cast", "AnsiCast", "TryCast"):
        return EC.Cast(_translate_expr(kids[0]),
                       _data_type(node.fields["dataType"]))
    if c == "CheckOverflow":
        # round/overflow-check to the target decimal type — the engine's
        # decimal cast has exactly those semantics
        return EC.Cast(_translate_expr(kids[0]),
                       _data_type(node.fields["dataType"]))
    if c in _PASSTHROUGH and kids:
        return _translate_expr(kids[0])
    if c == "InSet":
        return _in_set(node)
    special = _special_builders().get(c)
    if special is not None:
        return special(kids, node.fields)
    name = _CATALYST_ALIASES.get(c, c)
    if name is None:
        raise UnsupportedSparkPlan(f"expression {c}")
    cls = _engine_expr_classes().get(name)
    if cls is not None and _generic_applicable(name, cls):
        try:
            return cls(*[_translate_expr(k) for k in kids])
        except (TypeError, ValueError) as e:
            # constructors validate literal-ness/ranges with ValueError
            # (e.g. Conv bases); both mean "this shape isn't supported",
            # which must surface as fallback, not a crash
            raise UnsupportedSparkPlan(f"expression {c}: {e}") from e
    raise UnsupportedSparkPlan(f"expression {c}")


def translatable_expr_classes() -> set:
    """Catalyst class names this adapter can translate (the coverage test
    diffs this against the engine's override registry)."""
    names = {"AttributeReference", "Literal", "Alias", "Cast", "AnsiCast",
             "TryCast", "CheckOverflow", "InSet"}
    names |= _PASSTHROUGH
    names |= set(_special_builders())
    for nm, cls in _engine_expr_classes().items():
        if _generic_applicable(nm, cls):
            names.add(nm)
    names |= {c for c, tgt in _CATALYST_ALIASES.items() if tgt}
    return names


def _translate_agg_fn(node: _Node):
    """AggregateExpression(aggregateFunction=...) -> engine aggregate."""
    from ..expr import aggregates as AG
    if node.cls == "AggregateExpression":
        if str(node.fields.get("isDistinct", False)).lower() == "true":
            raise UnsupportedSparkPlan("DISTINCT aggregate")
        if node.fields.get("filter"):
            # dropping FILTER (WHERE ...) would silently aggregate
            # unfiltered rows
            raise UnsupportedSparkPlan("FILTER clause on aggregate")
        fn = _expr_tree(node.fields.get("aggregateFunction"))
        if fn is None and node.children:
            fn = node.children[0]
        return _translate_agg_fn(fn)
    fns = {"Sum": AG.Sum, "Min": AG.Min, "Max": AG.Max,
           "Average": AG.Average, "Count": AG.Count,
           "First": AG.First, "Last": AG.Last,
           "StddevPop": AG.StddevPop, "StddevSamp": AG.StddevSamp,
           "VariancePop": AG.VariancePop, "VarianceSamp": AG.VarianceSamp,
           "Skewness": AG.Skewness, "Kurtosis": AG.Kurtosis,
           "CollectList": AG.CollectList, "CollectSet": AG.CollectSet,
           "BoolAnd": AG.BoolAnd, "BoolOr": AG.BoolOr,
           "BitAndAgg": AG.BitAndAgg, "BitOrAgg": AG.BitOrAgg,
           "BitXorAgg": AG.BitXorAgg, "CountIf": AG.CountIf}
    if node.cls in fns:
        return fns[node.cls](_translate_expr(node.children[0]))
    if node.cls == "ApproximatePercentile":
        pct = _lit(node.children[1])
        acc = int(_lit(node.children[2])) if len(node.children) > 2 \
            else 10000
        return AG.ApproximatePercentile(_translate_expr(node.children[0]),
                                        pct, acc)
    raise UnsupportedSparkPlan(f"aggregate {node.cls}")


# ---------------------------------------------------------------------------
# plan translation
# ---------------------------------------------------------------------------

_TRANSPARENT = {"WholeStageCodegenExec", "InputAdapter",
                "AdaptiveSparkPlanExec", "ReusedExchangeExec",
                "ShuffleExchangeExec", "BroadcastExchangeExec",
                "ColumnarToRowExec", "RowToColumnarExec",
                "ShuffleQueryStageExec", "BroadcastQueryStageExec"}


def _join_type(s: str) -> str:
    s = str(s).strip().lower().replace("outer", "").strip()
    return {"inner": "inner", "left": "left", "right": "right",
            "full": "full", "leftsemi": "semi", "leftanti": "anti",
            "cross": "cross"}.get(s.replace(" ", ""), s)


def translate_spark_plan(plan_json, conf,
                         path_overrides: Optional[Dict[str, Sequence[str]]]
                         = None) -> N.PhysicalPlan:
    """Spark executedPlan.toJSON (string or parsed list) -> engine plan.
    `path_overrides` remaps relation identifiers/locations to local files
    (a real deployment reads the scan's own `location` field)."""
    arr = json.loads(plan_json) if isinstance(plan_json, str) else plan_json
    root = _build_tree(arr)
    return _translate(root, conf, path_overrides or {})


def _translate(node: _Node, conf, paths: Dict[str, Sequence[str]]
               ) -> N.PhysicalPlan:
    c = node.cls
    if c == "CollectLimitExec" and node.children:
        # keep the limit semantics rather than skipping it
        child = _translate(node.children[0], conf, paths)
        return N.CpuLimitExec(int(node.fields.get("limit", 0)), child)
    if c in _TRANSPARENT and node.children:
        return _translate(node.children[0], conf, paths)
    if c == "FileSourceScanExec":
        return _scan(node, conf, paths)
    if c == "ProjectExec":
        child = _translate(node.children[0], conf, paths)
        projs = [_translate_expr(e)
                 for e in _expr_list(node.fields.get("projectList"))]
        return N.CpuProjectExec(projs, child)
    if c == "FilterExec":
        child = _translate(node.children[0], conf, paths)
        cond = _translate_expr(_expr_tree(node.fields.get("condition")))
        return N.CpuFilterExec(cond, child)
    if c == "HashAggregateExec":
        return _aggregate(node, conf, paths)
    if c in ("SortMergeJoinExec", "ShuffledHashJoinExec",
             "BroadcastHashJoinExec"):
        left = _translate(node.children[0], conf, paths)
        right = _translate(node.children[1], conf, paths)
        lk = [_translate_expr(e)
              for e in _expr_list(node.fields.get("leftKeys"))]
        rk = [_translate_expr(e)
              for e in _expr_list(node.fields.get("rightKeys"))]
        cond = _expr_tree(node.fields.get("condition"))
        return N.CpuHashJoinExec(
            left, right, lk, rk, _join_type(node.fields.get("joinType")),
            condition=None if cond is None else _translate_expr(cond))
    if c == "SortExec":
        child = _translate(node.children[0], conf, paths)
        orders = _sort_orders(node)
        return N.CpuSortExec(orders, child)
    if c == "TakeOrderedAndProjectExec":
        child = _translate(node.children[0], conf, paths)
        orders = _sort_orders(node)
        limit = int(node.fields.get("limit", 0))
        plan = N.CpuLimitExec(limit, N.CpuSortExec(orders, child))
        projs = _expr_list(node.fields.get("projectList"))
        if projs:
            plan = N.CpuProjectExec([_translate_expr(e) for e in projs],
                                    plan)
        return plan
    if c in ("LocalLimitExec", "GlobalLimitExec"):
        child = _translate(node.children[0], conf, paths)
        return N.CpuLimitExec(int(node.fields.get("limit", 0)), child)
    if c == "UnionExec":
        return N.CpuUnionExec([_translate(ch, conf, paths)
                               for ch in node.children])
    if c == "RangeExec":
        return N.CpuRangeExec(int(node.fields.get("start", 0)),
                              int(node.fields.get("end", 0)),
                              int(node.fields.get("step", 1)))
    if c in ("BroadcastNestedLoopJoinExec", "CartesianProductExec"):
        left = _translate(node.children[0], conf, paths)
        right = _translate(node.children[1], conf, paths)
        cond = _expr_tree(node.fields.get("condition"))
        how = _join_type(node.fields.get("joinType", "inner")) \
            if c == "BroadcastNestedLoopJoinExec" else "cross"
        if cond is None and how == "inner":
            how = "cross"
        return N.CpuHashJoinExec(
            left, right, [], [], how,
            condition=None if cond is None else _translate_expr(cond))
    if c == "ExpandExec":
        return _expand(node, conf, paths)
    if c == "GenerateExec":
        return _generate(node, conf, paths)
    if c == "WindowExec":
        return _window(node, conf, paths)
    if c == "DataWritingCommandExec":
        return _write_command(node, conf, paths)
    raise UnsupportedSparkPlan(f"plan node {c}")


def _expand(node: _Node, conf, paths):
    """ExpandExec: N projections per input row (rollup/cube lowering)."""
    child = _translate(node.children[0], conf, paths)
    projections = []
    for proj in node.fields.get("projections") or []:
        projections.append([_translate_expr(e) for e in _expr_list(proj)])
    names = [e.fields["name"] for e in _expr_list(node.fields.get("output"))
             if e.cls == "AttributeReference"]
    if not projections or not names:
        raise UnsupportedSparkPlan("ExpandExec without projections/output")
    return N.CpuExpandExec(projections, names, child)


def _generate(node: _Node, conf, paths):
    """GenerateExec: explode/posexplode (+_outer via the `outer` field).
    The engine appends generator columns after ALL child columns with its
    own names, so a projection restores Spark's requiredChildOutput +
    generatorOutput shape and names."""
    from ..expr import base as EB
    from ..expr.collections import Explode
    child = _translate(node.children[0], conf, paths)
    gen = _expr_tree(node.fields.get("generator"))
    if gen is None:
        raise UnsupportedSparkPlan("GenerateExec without generator")
    position = gen.cls == "PosExplode"
    if gen.cls not in ("Explode", "PosExplode"):
        raise UnsupportedSparkPlan(f"generator {gen.cls}")
    outer = str(node.fields.get("outer", False)).lower() == "true"
    generator = Explode(_translate_expr(gen.children[0]),
                        position=position, outer=outer)
    plan = N.CpuGenerateExec(generator, child)
    gen_names = [e.fields["name"]
                 for e in _expr_list(node.fields.get("generatorOutput"))
                 if e.cls == "AttributeReference"]
    keep = [e.fields["name"]
            for e in _expr_list(node.fields.get("requiredChildOutput"))
            if e.cls == "AttributeReference"]
    n_child = len(child.output.names)
    n_gen = len(plan.output.names) - n_child
    if not gen_names or len(gen_names) != n_gen:
        # a silent fall-through would expose engine-internal column names
        # ('pos'/'col') to the parent plan's attribute binding
        raise UnsupportedSparkPlan(
            f"GenerateExec generatorOutput has {len(gen_names)} names "
            f"for {n_gen} generated columns")
    projs = []
    for nm in keep:
        projs.append(EB.AttributeReference(nm))
    for i, nm in enumerate(gen_names):
        projs.append(EB.Alias(
            EB.BoundReference(n_child + i,
                              plan.output.types[n_child + i]), nm))
    return N.CpuProjectExec(projs, plan)


def _frame_bound(b: _Node):
    name = b.cls
    if "UnboundedPreceding" in name or "UnboundedFollowing" in name:
        return None
    if "CurrentRow" in name:
        return 0
    if name == "Literal":
        v, _ = _literal_value(b)
        return int(v)
    raise UnsupportedSparkPlan(f"window frame bound {name}")


def _translate_window_fn(fn_node: _Node, spec_node: Optional[_Node]):
    from ..expr import windowexprs as WE
    frame = None
    if spec_node is not None and spec_node.children:
        last = spec_node.children[-1]
        if last.cls == "SpecifiedWindowFrame" and len(last.children) == 2:
            lo = _frame_bound(last.children[0])
            hi = _frame_bound(last.children[1])
            ftype = str(last.fields.get("frameType", "RowFrame"))
            frame = WE.RowFrame(lo, hi) if "Row" in ftype \
                else WE.RangeFrame(lo, hi)
    c = fn_node.cls
    if c == "RowNumber":
        return WE.RowNumber()
    if c == "Rank":
        return WE.Rank()
    if c == "DenseRank":
        return WE.DenseRank()
    if c == "PercentRank":
        return WE.PercentRank()
    if c == "CumeDist":
        return WE.CumeDist()
    if c == "NTile":
        v, _ = _literal_value(fn_node.children[0])
        return WE.NTile(int(v))
    if c in ("Lead", "Lag"):
        if str(fn_node.fields.get("ignoreNulls", False)).lower() == "true":
            raise UnsupportedSparkPlan(f"{c} IGNORE NULLS")
        expr = _translate_expr(fn_node.children[0])
        off = 1
        default = None
        if len(fn_node.children) > 1:
            if fn_node.children[1].cls != "Literal":
                raise UnsupportedSparkPlan(f"{c} non-literal offset")
            v, _ = _literal_value(fn_node.children[1])
            off = int(v)
        if len(fn_node.children) > 2:
            d = fn_node.children[2]
            if d.cls == "Literal":
                default, _ = _literal_value(d)
            else:  # silent null-default would be a wrong answer
                raise UnsupportedSparkPlan(f"{c} non-literal default")
        cls = WE.Lead if c == "Lead" else WE.Lag
        return cls(expr, off, default)
    if c == "NthValue":
        expr = _translate_expr(fn_node.children[0])
        v, _ = _literal_value(fn_node.children[1])
        ign = str(fn_node.fields.get("ignoreNulls", False)).lower() \
            == "true"
        return WE.NthValue(expr, int(v), ignore_nulls=ign, frame=frame)
    if c == "AggregateExpression":
        return WE.WindowAggregate(_translate_agg_fn(fn_node), frame)
    raise UnsupportedSparkPlan(f"window function {c}")


def _window(node: _Node, conf, paths):
    """WindowExec: each windowExpression is Alias(WindowExpression(fn,
    WindowSpecDefinition(..., frame)))."""
    child = _translate(node.children[0], conf, paths)
    fns = []
    for i, we in enumerate(_expr_list(node.fields.get("windowExpression"))):
        name = f"w{i}"
        inner = we
        if we.cls == "Alias":
            name = we.fields.get("name", name)
            inner = we.children[0]
        if inner.cls != "WindowExpression" or not inner.children:
            raise UnsupportedSparkPlan(
                f"window expression {inner.cls}")
        fn_node = inner.children[0]
        spec = inner.children[1] if len(inner.children) > 1 else None
        fns.append((_translate_window_fn(fn_node, spec), name))
    part = [_translate_expr(e)
            for e in _expr_list(node.fields.get("partitionSpec"))]
    orders = _sort_orders(node, field="orderSpec")
    return N.CpuWindowExec(fns, part, orders, child)


def _write_command(node: _Node, conf, paths):
    """DataWritingCommandExec(InsertIntoHadoopFsRelationCommand): the
    write-command exec (`GpuDataWritingCommandExec.scala` analog). The
    destination can be remapped through path_overrides under the
    reserved key '__write_output__' — a dunder name no relation
    identifier can collide with (relation ids share the same dict)."""
    from ..io.writer import CpuWriteFilesExec
    cmd = _expr_tree(node.fields.get("cmd"))
    if cmd is None or cmd.cls != "InsertIntoHadoopFsRelationCommand":
        raise UnsupportedSparkPlan(
            f"write command {None if cmd is None else cmd.cls}")
    child = _translate(node.children[0], conf, paths)
    fmt = str(cmd.fields.get("fileFormat", "parquet")).lower()
    for known in ("parquet", "orc", "csv"):  # the engine's writer formats
        if known in fmt:
            fmt = known
            break
    else:
        raise UnsupportedSparkPlan(f"write format {fmt}")
    out = paths.get("__write_output__")
    out_path = out[0] if out else cmd.fields.get("outputPath")
    if not out_path:
        raise UnsupportedSparkPlan("write command without outputPath")
    part_cols = [e.fields["name"] for e in
                 _expr_list(cmd.fields.get("partitionColumns"))
                 if e.cls == "AttributeReference"]
    mode = str(cmd.fields.get("mode", "ErrorIfExists"))
    mode = {"append": "append", "overwrite": "overwrite",
            "ignore": "ignore"}.get(mode.lower(), "error")
    return CpuWriteFilesExec(str(out_path), fmt, part_cols, mode, child,
                             conf)


def _sort_orders(node: _Node, field: str = "sortOrder"
                 ) -> List[Tuple[Any, bool, bool]]:
    orders = []
    for so in _expr_list(node.fields.get(field)):
        # SortOrder(child, direction, nullOrdering)
        e = _translate_expr(so.children[0])
        asc = "Asc" in str(so.fields.get("direction", "Ascending"))
        nf = "First" in str(so.fields.get("nullOrdering",
                                          "NullsFirst" if asc
                                          else "NullsLast"))
        orders.append((e, asc, nf))
    return orders


def _scan(node: _Node, conf, paths: Dict[str, Sequence[str]]):
    from ..io.parquet import parquet_scan_plan
    f = node.fields
    fmt = str(f.get("relation", f.get("fileFormat", "parquet"))).lower()
    # output schema from the scan's output attribute list
    columns = [e.fields["name"] for e in _expr_list(f.get("output"))
               if e.cls == "AttributeReference"]
    ident = f.get("tableIdentifier") or f.get("location") or "scan"
    local = paths.get(str(ident)) or paths.get("*")
    if local is None:
        raise UnsupportedSparkPlan(
            f"no local path mapping for relation {ident!r}")
    if "parquet" not in fmt and "hadoopfsrelation" not in fmt:
        raise UnsupportedSparkPlan(f"scan format {fmt}")
    return parquet_scan_plan(list(local), conf, columns=columns or None)


def _aggregate(node: _Node, conf, paths: Dict[str, Sequence[str]]):
    """Partial/Final HashAggregate pairs collapse: the engine's aggregate
    handles partial/final split itself (the exchange between them is
    transparent here, like the override rewrite re-plans distribution)."""
    f = node.fields
    child_node = node.children[0]
    # descend through the partial half + exchanges to the true input
    probe = child_node
    while probe.cls in _TRANSPARENT and probe.children:
        probe = probe.children[0]
    if probe.cls == "HashAggregateExec":
        inner = probe
        probe2 = inner.children[0]
        child = _translate(probe2, conf, paths)
    else:
        child = _translate(child_node, conf, paths)
    keys = [_translate_expr(e)
            for e in _expr_list(f.get("groupingExpressions"))]
    aggs = []
    for i, ae in enumerate(_expr_list(f.get("aggregateExpressions"))):
        fn = _translate_agg_fn(ae)
        aggs.append(N.AggExpr(fn, f"agg{i}"))
    # result names from resultExpressions' aliases when present
    names = [e.fields.get("name") for e in
             _expr_list(f.get("resultExpressions"))
             if e.cls == "Alias"]
    if len(names) == len(aggs):  # only an unambiguous 1:1 mapping renames
        for i, nm in enumerate(names):
            if nm:
                aggs[i] = N.AggExpr(aggs[i].func, nm)
    return N.CpuHashAggregateExec(keys, aggs, child)
