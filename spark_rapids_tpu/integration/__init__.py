from .spark_plan import translate_spark_plan  # noqa: F401
