"""ICI collective exchange — partitioned shuffle and broadcast as XLA collectives.

This replaces the reference's UCX p2p transport (`shuffle-plugin/.../UCX.scala`,
client/server state machines in `shuffle/RapidsShuffleClient.scala` /
`RapidsShuffleServer.scala`) with a single compiled collective: every device
buckets its rows by destination into fixed-capacity slots and one
`lax.all_to_all` moves all of it over ICI simultaneously — there is no
metadata-request/transfer-request round trip because slot shapes are static and
known to the compiler (the flatbuffer TableMeta layer exists in the reference
precisely because sizes are dynamic there).

Shapes: a device's local shard is a set of leaf arrays with leading dim `cap`
(rows past the logical count are padding). Bucketing produces `[ndev, slot_cap]`
leading dims; all_to_all swaps the leading device axis; compaction restores a
single `[ndev * slot_cap]` local shard + count. slot_cap = cap is always safe (a
device holds at most cap rows total); smaller slot_caps bound skew but can
overflow a slot, so the exchange computes an ON-DEVICE overflow flag (psum over
the mesh) that host callers MUST check — the engine retries with a doubled
slot_cap rather than ever dropping rows (the reference can never drop shuffle
rows either).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import SHUFFLE_AXIS

try:  # jax >= 0.6 public API
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)

__all__ = ["bucketize_by_partition", "compact_received", "all_to_all_exchange",
           "broadcast_all_gather", "build_exchange_fn"]


# ---------------------------------------------------------------------------
# Device-local building blocks (plain jnp; composable under jit / shard_map)
# ---------------------------------------------------------------------------

def _scatter_rows(leaf, slot_index, out_rows: int):
    """Scatter rows of `leaf` ([cap, ...]) to `slot_index` positions in a
    zeroed [out_rows, ...] buffer; indices == out_rows drop."""
    out = jnp.zeros((out_rows,) + leaf.shape[1:], leaf.dtype)
    return out.at[slot_index].set(leaf, mode="drop")


def bucketize_by_partition(leaves: Sequence[Any], pid, ndev: int,
                           slot_cap: int):
    """Group rows by destination into [ndev, slot_cap, ...] slot buffers.

    pid is int32[cap] with -1 marking padding rows and values REQUIRED to be in
    [-1, ndev): a partitioner built for more partitions than mesh devices would
    silently lose its out-of-range rows here, so callers must size the
    partitioner to the mesh. Returns (slotted_leaves, send_counts[int32[ndev]],
    overflowed bool[]). Rows beyond slot_cap for one destination do not fit in
    the slot buffers; `overflowed` reports that so callers can retry with a
    larger slot_cap (never silently proceed on overflow)."""
    cap = pid.shape[0]
    valid = pid >= 0
    key = jnp.where(valid, pid, ndev)
    order = jnp.argsort(key, stable=True)
    key_sorted = key[order]
    counts = jnp.bincount(key, length=ndev + 1)[:ndev].astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    pos = jnp.arange(cap, dtype=jnp.int32)
    # rank of each sorted row within its destination group
    rank = pos - offsets[jnp.clip(key_sorted, 0, ndev - 1)]
    in_slot = (key_sorted < ndev) & (rank < slot_cap)
    slot_index = jnp.where(in_slot, key_sorted * slot_cap + rank,
                           ndev * slot_cap)  # == out_rows -> dropped
    slotted = [
        _scatter_rows(leaf[order], slot_index, ndev * slot_cap)
        .reshape((ndev, slot_cap) + leaf.shape[1:])
        for leaf in leaves
    ]
    overflowed = jnp.any(counts > slot_cap)
    return slotted, jnp.minimum(counts, slot_cap), overflowed


def compact_received(leaves: Sequence[Any], recv_counts):
    """[ndev, slot_cap, ...] received slots -> single compacted local shard.

    Row j of source block s is live iff j < recv_counts[s]. Returns
    (compacted_leaves with leading dim ndev*slot_cap, total int32)."""
    ndev, slot_cap = leaves[0].shape[0], leaves[0].shape[1]
    flat = [l.reshape((ndev * slot_cap,) + l.shape[2:]) for l in leaves]
    j = jnp.arange(ndev * slot_cap, dtype=jnp.int32)
    live = (j % slot_cap) < recv_counts[j // slot_cap]
    order = jnp.argsort(~live, stable=True)
    total = jnp.sum(recv_counts).astype(jnp.int32)
    return [f[order] for f in flat], total


# ---------------------------------------------------------------------------
# Collectives (must run under shard_map with the mesh axis bound)
# ---------------------------------------------------------------------------

def all_to_all_exchange(leaves: Sequence[Any], pid, ndev: int,
                        slot_cap: Optional[int] = None,
                        axis: str = SHUFFLE_AXIS):
    """Full partitioned exchange for one device's shard; call under shard_map.

    bucket -> lax.all_to_all over ICI -> compact. Returns (leaves', total,
    overflowed) where leaves' have leading dim ndev * slot_cap, `total` is the
    live row count on this device after the exchange, and `overflowed` is a
    mesh-global bool (psum'd) that is True iff ANY device overflowed a slot —
    host callers must check it and retry with a larger slot_cap (rows are never
    silently dropped)."""
    cap = pid.shape[0]
    slot_cap = slot_cap or cap
    slotted, send_counts, local_ov = bucketize_by_partition(
        leaves, pid, ndev, slot_cap)
    recv = [jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                               tiled=False)
            for s in slotted]
    recv_counts = jax.lax.all_to_all(send_counts, axis, split_axis=0,
                                     concat_axis=0, tiled=True)
    overflowed = jax.lax.psum(local_ov.astype(jnp.int32), axis) > 0
    out, total = compact_received(recv, recv_counts)
    return out, total, overflowed


def broadcast_all_gather(leaves: Sequence[Any], count, ndev: int,
                         axis: str = SHUFFLE_AXIS):
    """Replicate every device's shard to all devices (broadcast build side,
    `GpuBroadcastExchangeExec.scala:320` analog — but over ICI all_gather rather
    than host serialization through the driver). Call under shard_map.

    Returns (leaves', total): leading dim ndev*cap, rows compacted."""
    gathered = [jax.lax.all_gather(l, axis, axis=0, tiled=False)
                for l in leaves]
    counts = jax.lax.all_gather(count, axis, axis=0, tiled=False)
    return compact_received(gathered, counts)


# ---------------------------------------------------------------------------
# jit-compiled exchange entry
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def build_exchange_fn(mesh: Mesh, ndev: int, slot_cap: Optional[int] = None,
                      axis: str = SHUFFLE_AXIS) -> Callable:
    """Compile a partitioned-exchange program over `mesh`. Memoized per
    (mesh, ndev, slot_cap, axis): a fresh jax.jit closure per call would
    retrace/recompile the collective on every exchange execution.

    Returned fn: (leaves: list of [ndev*cap, ...] globally-sharded arrays,
    pid: int32[ndev*cap] sharded alike) -> (exchanged leaves sharded alike with
    per-device leading dim ndev*slot_cap, counts int32[ndev] = live rows per
    device, overflowed bool[] replicated). The per-leaf sharding is rows-split
    along the mesh axis; XLA lowers the inner all_to_all to ICI transfers.
    Callers MUST check `overflowed` and retry with a larger slot_cap."""

    def step(leaves, pid):
        out, total, ov = all_to_all_exchange(leaves, pid, ndev, slot_cap, axis)
        return out, total[None], ov

    sharded = shard_map(
        step, mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P()),
    )
    from ..compile import instance_jit, kernel_key
    return instance_jit(
        sharded, op="parallel.exchange",
        key=kernel_key(repr(mesh), ndev, slot_cap, axis))
