"""Device mesh construction.

The reference discovers peers through executor heartbeats with the driver
(`RapidsShuffleHeartbeatManager.scala`, `Plugin.scala:227-239`) because executors are
independent JVMs. On TPU the topology is declared, not discovered: a
`jax.sharding.Mesh` over the slice's chips, with ICI links between neighbours. One
1-D "shuffle" axis covers partitioned exchange (all-to-all) and broadcast
(all_gather); multi-host slices extend the same mesh over DCN transparently via
jax.distributed — the collective compiles identically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHUFFLE_AXIS = "shuffle"


def mesh_devices(n_devices: Optional[int] = None) -> Sequence[jax.Device]:
    devs = jax.devices()
    if n_devices is None:
        return devs
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} present "
            f"(hint: tests use xla_force_host_platform_device_count)")
    return devs[:n_devices]


def make_mesh(n_devices: Optional[int] = None,
              axis: str = SHUFFLE_AXIS) -> Mesh:
    """1-D mesh over the slice for partitioned exchange. On a real pod the device
    order from jax.devices() follows the physical torus so neighbouring mesh
    positions are ICI neighbours."""
    devs = mesh_devices(n_devices)
    return Mesh(np.array(devs), (axis,))


_CONF_MESH: dict = {}


def invalidate_cache() -> None:
    """Drop the conf->Mesh memo. Hooked into `TpuConf.set` for every
    `spark.rapids.tpu.mesh.*` key (config.py), the same conf-generation
    invalidation the padding memo got in PR 3: a mid-session conf change
    must never serve a mesh built for the previous configuration."""
    _CONF_MESH.clear()


def mesh_from_conf(conf) -> Optional[Mesh]:
    """The session's active mesh, from `spark.rapids.tpu.mesh.shape`
    ('shuffle=8' or just '8'; empty/1 = single device, no mesh). The engine
    routes planned exchanges through ICI collectives when a mesh is active
    (plan-driven distributed execution, not a hand-built program). Cached per
    shape — Mesh identity matters for jax's compilation cache; the cache is
    dropped by `invalidate_cache()` whenever a mesh conf key changes."""
    shape = (conf.get("spark.rapids.tpu.mesh.shape") or "").strip()
    if not shape:
        return None
    part = shape.split(",")[0].strip()
    n = int(part.split("=")[-1])
    if n <= 1:
        return None
    if shape not in _CONF_MESH:
        _CONF_MESH[shape] = make_mesh(n)
    return _CONF_MESH[shape]
