"""Device mesh construction.

The reference discovers peers through executor heartbeats with the driver
(`RapidsShuffleHeartbeatManager.scala`, `Plugin.scala:227-239`) because executors are
independent JVMs. On TPU the topology is declared, not discovered: a
`jax.sharding.Mesh` over the slice's chips, with ICI links between neighbours. One
1-D "shuffle" axis covers partitioned exchange (all-to-all) and broadcast
(all_gather); multi-host slices extend the same mesh over DCN transparently via
jax.distributed — the collective compiles identically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHUFFLE_AXIS = "shuffle"


def mesh_devices(n_devices: Optional[int] = None) -> Sequence[jax.Device]:
    devs = jax.devices()
    if n_devices is None:
        return devs
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} present "
            f"(hint: tests use xla_force_host_platform_device_count)")
    return devs[:n_devices]


def make_mesh(n_devices: Optional[int] = None,
              axis: str = SHUFFLE_AXIS) -> Mesh:
    """1-D mesh over the slice for partitioned exchange. On a real pod the device
    order from jax.devices() follows the physical torus so neighbouring mesh
    positions are ICI neighbours."""
    devs = mesh_devices(n_devices)
    return Mesh(np.array(devs), (axis,))
