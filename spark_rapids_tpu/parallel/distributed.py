"""Distributed query step — the framework's flagship SPMD program.

The reference's headline workload is a partitioned hash-join + aggregation riding
its shuffle (`GpuShuffledHashJoinExec.scala` fed by
`GpuShuffleExchangeExecBase.scala`, BASELINE workload #1/#3). This module compiles
that whole pipeline into ONE XLA program over a device mesh:

    per-chip shard of fact/dim rows
      -> murmur3 partition ids (Spark-exact, expr/hashing.py)
      -> lax.all_to_all over ICI      (the shuffle)
      -> co-partitioned local join    (equality matrix contraction -> MXU)
      -> grouped partial aggregation  (segment sums on-chip)
      -> psum over the mesh           (final merge)

Contrast with the reference, where each stage is a separate host-orchestrated
phase with serialization boundaries (write side / transport / read side /
build / probe); here XLA sees the dataflow end-to-end and can overlap the
collective with compute. This is what `__graft_entry__.dryrun_multichip`
compiles and what bench.py scales up on real hardware.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..expr.hashing import hash_vecs
from ..expr.base import Vec
from .. import types as T
from .collective import all_to_all_exchange, shard_map
from .mesh import SHUFFLE_AXIS

__all__ = ["QueryStepInputs", "make_distributed_query_step",
           "make_example_inputs", "reference_query_result"]


class QueryStepInputs(NamedTuple):
    """Globally-sharded inputs (leading dim = ndev * cap, split over the mesh).

    fact: sales-like table (join key, group key, measure); dim: lookup table
    (join key, weight). counts are per-device live-row counts, shape [ndev]."""
    fact_key: jax.Array     # int64[N]
    fact_grp: jax.Array     # int32[N]  in [0, n_groups)
    fact_val: jax.Array     # float64[N]
    fact_count: jax.Array   # int32[ndev]
    dim_key: jax.Array      # int64[M]
    dim_weight: jax.Array   # float64[M]
    dim_count: jax.Array    # int32[ndev]


def _pids(key, count_scalar, ndev: int):
    """Spark hashpartitioning(key, ndev) ids; padding rows -> -1."""
    cap = key.shape[0]
    live = jnp.arange(cap, dtype=jnp.int32) < count_scalar
    h = hash_vecs(jnp, [Vec(T.LongType(), key, live)], np.uint32(42))
    pid = ((h.astype(jnp.int32) % ndev) + ndev) % ndev
    return jnp.where(live, pid, -1)


def make_distributed_query_step(mesh: Mesh, ndev: int, n_groups: int,
                                axis: str = SHUFFLE_AXIS):
    """Compile the exchange->join->aggregate step over `mesh`.

    Returns (fn, shard_fn): fn maps QueryStepInputs -> (group_sums f64[n_groups],
    joined_rows i64[]) both replicated; shard_fn places host arrays with the
    right NamedSharding."""

    def device_step(fact_key, fact_grp, fact_val, fact_count,
                    dim_key, dim_weight, dim_count):
        fcnt = fact_count[0]
        dcnt = dim_count[0]
        # ---- shuffle: hash-exchange both sides by join key over ICI
        fpid = _pids(fact_key, fcnt, ndev)
        (fact_key2, fact_grp2, fact_val2), fn_total, _ = all_to_all_exchange(
            [fact_key, fact_grp, fact_val], fpid, ndev, axis=axis)
        dpid = _pids(dim_key, dcnt, ndev)
        (dim_key2, dim_weight2), dn_total, _ = all_to_all_exchange(
            [dim_key, dim_weight], dpid, ndev, axis=axis)

        # ---- co-partitioned inner join (fact x dim on key), MXU-shaped:
        # equality matrix [nf, nd] contracted against dim weights. Unique dim
        # keys make this exact; duplicate dim keys sum weights (weighted join).
        f_live = jnp.arange(fact_key2.shape[0], dtype=jnp.int32) < fn_total
        d_live = jnp.arange(dim_key2.shape[0], dtype=jnp.int32) < dn_total
        eq = (fact_key2[:, None] == dim_key2[None, :]) & \
            f_live[:, None] & d_live[None, :]
        joined_w = eq.astype(jnp.float64) @ dim_weight2  # [nf] MXU contraction
        matched = eq.any(axis=1)

        # ---- grouped partial aggregate: sum(val * weight) per group key
        contrib = jnp.where(matched, fact_val2 * joined_w, 0.0)
        seg = jnp.clip(fact_grp2, 0, n_groups - 1)
        partial = jax.ops.segment_sum(contrib, seg, num_segments=n_groups)
        rows = jnp.sum(matched & f_live).astype(jnp.int64)

        # ---- final merge across chips
        total = jax.lax.psum(partial, axis)
        total_rows = jax.lax.psum(rows, axis)
        return total, total_rows

    from ..compile import instance_jit, kernel_key
    fn = instance_jit(shard_map(
        device_step, mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis), P(axis)),
        out_specs=(P(), P()),
    ), op="parallel.query_step",
        key=kernel_key(repr(mesh), axis, n_groups))

    def shard_fn(inputs: QueryStepInputs) -> QueryStepInputs:
        sh = NamedSharding(mesh, P(axis))
        return QueryStepInputs(*(jax.device_put(jnp.asarray(x), sh)
                                 for x in inputs))

    return fn, shard_fn


def make_example_inputs(ndev: int, cap: int, n_groups: int,
                        seed: int = 0, dim_cap: Optional[int] = None,
                        key_space: Optional[int] = None) -> QueryStepInputs:
    """Synthetic q5-ish inputs: every device shard full; dim keys unique."""
    rng = np.random.default_rng(seed)
    dim_cap = dim_cap or cap
    n, m = ndev * cap, ndev * dim_cap
    key_space = key_space or max(2 * m, 16)
    fact_key = rng.integers(0, key_space, size=n).astype(np.int64)
    fact_grp = rng.integers(0, n_groups, size=n).astype(np.int32)
    fact_val = rng.normal(1.0, 0.25, size=n).astype(np.float64)
    dim_key = rng.permutation(key_space)[:m].astype(np.int64)
    dim_weight = rng.uniform(0.5, 1.5, size=m).astype(np.float64)
    return QueryStepInputs(
        fact_key, fact_grp, fact_val,
        np.full(ndev, cap, np.int32),
        dim_key, dim_weight,
        np.full(ndev, dim_cap, np.int32))


def reference_query_result(inp: QueryStepInputs, n_groups: int):
    """Numpy oracle for the distributed step (independent algorithm: dict join)."""
    w = {int(k): float(v) for k, v in zip(inp.dim_key, inp.dim_weight)}
    sums = np.zeros(n_groups, np.float64)
    rows = 0
    for k, g, v in zip(inp.fact_key, inp.fact_grp, inp.fact_val):
        wk = w.get(int(k))
        if wk is not None:
            sums[g] += float(v) * wk
            rows += 1
    return sums, rows
