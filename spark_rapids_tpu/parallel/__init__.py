"""Distributed execution layer — the TPU-native counterpart of the reference's
shuffle & transport stack (SURVEY.md §2.7).

Where the reference moves partitioned batches between executor JVMs over UCX/RDMA
(`shuffle-plugin/.../UCX.scala`) or host-serialized Spark shuffle
(`RapidsShuffleInternalManagerBase.scala`), this layer moves them between TPU chips
with XLA collectives over ICI: partitioned exchange is a `shard_map`-wrapped
`lax.all_to_all` over a `jax.sharding.Mesh`; broadcast replication is `all_gather`.
Variable partition sizes ride the fixed-capacity slot discipline (pad-and-slice,
ARCHITECTURE.md #1) so everything stays statically shaped for XLA.
"""

from .partitioning import (HashPartitioning, RangePartitioning,
                           RoundRobinPartitioning, SinglePartitioning,
                           TpuPartitioning)
from .mesh import make_mesh, mesh_devices
from .collective import (all_to_all_exchange, broadcast_all_gather,
                         bucketize_by_partition, compact_received)

__all__ = [
    "TpuPartitioning", "HashPartitioning", "RangePartitioning",
    "RoundRobinPartitioning", "SinglePartitioning",
    "make_mesh", "mesh_devices",
    "all_to_all_exchange", "broadcast_all_gather", "bucketize_by_partition",
    "compact_received",
]
