"""TPU partitioners — per-row partition-id kernels.

Counterpart of the reference's GPU partitioners (`GpuHashPartitioningBase.scala`,
`GpuRangePartitioner.scala`, `GpuRoundRobinPartitioning.scala`,
`GpuSinglePartitioning.scala`; registered at `GpuOverrides.scala:3536-3575`). Each
partitioner maps a device batch to an int32 partition id per row; the exchange layer
then either slices locally (host path) or buckets + all-to-alls (ICI path).

Hash placement must match CPU Spark exactly (same rows land in the same partition)
— that is what makes differential testing of distributed plans possible — so
HashPartitioning uses the Spark-exact Murmur3 from expr/hashing.py with Spark's
seed 42 and pmod semantics.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from .. import types as T
from ..columnar.batch import ColumnarBatch
from ..expr.base import EvalContext, Expression, Vec, bind_references
from ..expr.hashing import hash_vecs

__all__ = ["TpuPartitioning", "HashPartitioning", "RangePartitioning",
           "RoundRobinPartitioning", "SinglePartitioning"]


class TpuPartitioning:
    """Base partitioner: produce int32 partition ids for every row of a batch.

    Rows past the logical count (padding) get id -1 so downstream bucketing can
    drop them without consulting the row mask again."""

    num_partitions: int

    def partition_ids(self, xp, vecs: Sequence[Vec], row_mask):
        raise NotImplementedError

    def ids_for_batch(self, xp, batch: ColumnarBatch):
        vecs = [Vec.from_column(c) for c in batch.columns]
        mask = batch.row_mask()
        pid = self.partition_ids(xp, vecs, mask)
        return xp.where(mask, pid, xp.asarray(-1, np.int32))


def _pmod(xp, h, n: int):
    """Spark pmod: ((h % n) + n) % n on int32."""
    r = h.astype(np.int32) % np.int32(n)
    return ((r + np.int32(n)) % np.int32(n)).astype(np.int32)


@dataclasses.dataclass
class HashPartitioning(TpuPartitioning):
    """pmod(murmur3(keys, seed=42), n) — `GpuHashPartitioningBase.scala` semantics
    (which mirror Spark's HashPartitioning over Murmur3Hash(exprs, 42))."""

    key_ordinals: Sequence[int]
    num_partitions: int

    def partition_ids(self, xp, vecs, row_mask):
        keys = [vecs[i] for i in self.key_ordinals]
        h = hash_vecs(xp, keys, np.uint32(42))
        return _pmod(xp, h.astype(np.int32), self.num_partitions)

    @staticmethod
    def from_exprs(keys: Sequence[Expression], schema, num_partitions: int
                   ) -> "HashPartitioning":
        from ..expr.base import BoundReference
        ords = []
        for k in keys:
            b = bind_references(k, schema)
            if not isinstance(b, BoundReference):
                raise ValueError("partition keys must be column references "
                                 "after planning (planner projects first)")
            ords.append(b.ordinal)
        return HashPartitioning(tuple(ords), num_partitions)

    def __repr__(self):
        return f"hashpartitioning({list(self.key_ordinals)}, {self.num_partitions})"


@dataclasses.dataclass
class RangePartitioning(TpuPartitioning):
    """Range partitioning against precomputed bounds (`GpuRangePartitioner.scala`:
    bounds come from driver-side sampling, the device does a vectorized
    searchsorted). Single sort column, ascending/descending + nulls-first, which
    covers Spark's common ORDER BY exchange."""

    ordinal: int
    bounds: np.ndarray          # ascending upper bounds, len n_parts - 1
    ascending: bool = True
    nulls_first: bool = True

    def __post_init__(self):
        self.num_partitions = len(self.bounds) + 1

    def partition_ids(self, xp, vecs, row_mask):
        v = vecs[self.ordinal]
        if v.is_string:
            raise TypeError("range partitioning on STRING is not supported on "
                            "device (planner falls back to CPU)")
        data = v.data
        bounds = xp.asarray(self.bounds)
        pid = xp.searchsorted(bounds, data, side="right").astype(np.int32)
        if not self.ascending:
            pid = np.int32(self.num_partitions - 1) - pid
        null_pid = np.int32(0 if self.nulls_first else self.num_partitions - 1)
        return xp.where(v.validity, pid, null_pid)

    @staticmethod
    def from_sample(vec_np: Vec, ordinal: int, num_partitions: int,
                    ascending: bool = True, nulls_first: bool = True
                    ) -> "RangePartitioning":
        """Driver-side bound computation from a host sample (the reference samples
        via Spark's RangePartitioner then evaluates bounds on device)."""
        if vec_np.is_string:
            raise TypeError("range partitioning on STRING is not supported on "
                            "device (planner falls back to CPU)")
        data = np.asarray(vec_np.data)[np.asarray(vec_np.validity)]
        if data.size == 0:
            bounds = np.zeros(max(num_partitions - 1, 0), dtype=data.dtype)
        else:
            qs = np.linspace(0, 1, num_partitions + 1)[1:-1]
            bounds = np.asarray(np.quantile(data, qs, method="nearest"),
                                dtype=data.dtype)
        return RangePartitioning(ordinal, bounds, ascending, nulls_first)

    def __repr__(self):
        return f"rangepartitioning(col{self.ordinal}, {self.num_partitions})"


@dataclasses.dataclass
class RoundRobinPartitioning(TpuPartitioning):
    """(start + row_position) % n (`GpuRoundRobinPartitioning.scala`). start is
    chosen per input partition like Spark picks a random start per task; fixed
    here for determinism under the differential harness."""

    num_partitions: int
    start: int = 0

    def partition_ids(self, xp, vecs, row_mask):
        n = row_mask.shape[0]
        pos = xp.arange(n, dtype=np.int32)
        return (np.int32(self.start) + pos) % np.int32(self.num_partitions)

    def __repr__(self):
        return f"roundrobin({self.num_partitions})"


@dataclasses.dataclass
class SinglePartitioning(TpuPartitioning):
    """Everything to partition 0 (`GpuSinglePartitioning.scala`)."""

    num_partitions: int = 1

    def partition_ids(self, xp, vecs, row_mask):
        return xp.zeros(row_mask.shape[0], np.int32)

    def __repr__(self):
        return "singlepartitioning"
