"""Typed configuration registry.

Design mirrors the reference's `RapidsConf.scala` (ConfBuilder/ConfEntry, reference
`sql-plugin/.../RapidsConf.scala:120-307`; registry `:310+`; docs generation
`RapidsConf.help` `:1874`): every knob is a declared, typed `ConfEntry` with a doc string,
default, optional value-check, `internal` and `startup_only` flags; `TpuConf` wraps a plain
dict of user settings and exposes typed accessors; `generate_docs()` emits
`docs/configs.md`. Per-operator and per-expression enable keys are auto-registered by the
planning layer (`spark.rapids.sql.exec.*` / `.expression.*`), as in the reference.

Key namespace intentionally matches the reference (`spark.rapids.*`) so that reference
users' configs translate 1:1; TPU-specific keys live under `spark.rapids.tpu.*`.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["ConfEntry", "TpuConf", "register", "entries", "generate_docs", "get_default_conf"]

_REGISTRY: Dict[str, "ConfEntry"] = {}
_LOCK = threading.Lock()

_SIZE_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([kmgt]?i?b?)$", re.IGNORECASE)
_SIZE_MULT = {
    "": 1, "b": 1,
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_bytes(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v).strip())
    if not m:
        raise ValueError(f"cannot parse byte size: {v!r}")
    return int(float(m.group(1)) * _SIZE_MULT[m.group(2).lower()])


def _convert(value: Any, typ: str) -> Any:
    if typ == "bool":
        if isinstance(value, bool):
            return value
        return str(value).strip().lower() in ("true", "1", "yes")
    if typ == "int":
        return int(value)
    if typ == "double":
        return float(value)
    if typ == "bytes":
        return parse_bytes(value)
    return str(value)


class ConfEntry:
    def __init__(self, key: str, typ: str, default: Any, doc: str,
                 internal: bool = False, startup_only: bool = False,
                 check_values: Optional[Sequence[Any]] = None,
                 checker: Optional[Callable[[Any], bool]] = None):
        self.key = key
        self.typ = typ
        self.default = default
        self.doc = doc
        self.internal = internal
        self.startup_only = startup_only
        self.check_values = tuple(check_values) if check_values else None
        self.checker = checker

    def convert(self, raw: Any) -> Any:
        v = _convert(raw, self.typ)
        if self.check_values is not None and v not in self.check_values:
            raise ValueError(
                f"{self.key}={v!r} not in allowed values {self.check_values}")
        if self.checker is not None and not self.checker(v):
            raise ValueError(f"{self.key}={v!r} failed validation")
        return v


def register(key: str, typ: str, default: Any, doc: str, **kw) -> ConfEntry:
    with _LOCK:
        if key in _REGISTRY:
            return _REGISTRY[key]
        e = ConfEntry(key, typ, default, doc, **kw)
        _REGISTRY[key] = e
        return e


def entries() -> Dict[str, ConfEntry]:
    return dict(_REGISTRY)


# --------------------------------------------------------------------------------------
# Core key registry. Names follow the reference where a counterpart exists.
# --------------------------------------------------------------------------------------

register("spark.rapids.sql.enabled", "bool", True,
         "Enable the TPU columnar rewrite of SQL physical plans.")
register("spark.rapids.sql.mode", "string", "executeOnGPU",
         "executeOnGPU runs converted plans on TPU; explainOnly only tags and reports "
         "what would run on TPU without converting.",
         check_values=("executeOnGPU", "explainOnly"))
register("spark.rapids.sql.explain", "string", "NONE",
         "Explain output for the plan rewrite: NONE, NOT_ON_GPU (only fallback reasons), "
         "ALL.", check_values=("NONE", "NOT_ON_GPU", "ALL"))
register("spark.rapids.sql.batchSizeBytes", "bytes", 1 << 30,
         "Target device batch size for coalescing (reference default 1GiB).")
register("spark.rapids.sql.batchSizeRows", "int", 1 << 20,
         "Target max rows per device batch.")
register("spark.rapids.sql.concurrentGpuTasks", "int", 2,
         "Number of tasks admitted concurrently to the TPU (GpuSemaphore analog).")
register("spark.rapids.sql.metrics.level", "string", "MODERATE",
         "Operator metric verbosity: ESSENTIAL, MODERATE, DEBUG.",
         check_values=("ESSENTIAL", "MODERATE", "DEBUG"))
register("spark.rapids.tpu.metrics.eventLog.dir", "string", "",
         "Directory for the per-query JSONL profile event log (one "
         "schema-versioned record per query/operator/span, append-only). "
         "Setting it activates the query profiler; empty disables both "
         "the log and all span overhead. scripts/profile_report.sh "
         "consumes these logs offline.")
register("spark.rapids.tpu.metrics.profile.enabled", "bool", False,
         "Collect the in-memory query profile (span tree + per-operator "
         "metric deltas, TpuSession.explain_profile()) without writing an "
         "event log. Implied by spark.rapids.tpu.metrics.eventLog.dir.")
register("spark.rapids.tpu.metrics.spans.kernel.enabled", "bool", False,
         "Also record one span per compiled-kernel invocation (kind="
         "'kernel'). High-cardinality: one record per batch per kernel; "
         "meant for deep dives, not steady-state profiling.")
register("spark.rapids.tpu.metrics.eventLog.maxBytes", "bytes", 0,
         "Size cap for the live per-process event-log file: an append "
         "that would push it past this rotates the file to '.1' "
         "(shifting older generations to '.2', ...), bounding a long-"
         "lived server's log on disk. 0 (default) keeps the historical "
         "unbounded append. profile_report reads rotated generations "
         "alongside live files.")
register("spark.rapids.tpu.metrics.eventLog.maxFiles", "int", 10,
         "Rotated event-log generations kept per process ('.1'..'.N'); "
         "the oldest falls off at the next rotation.")

# Live telemetry ---------------------------------------------------------------------
register("spark.rapids.tpu.telemetry.enabled", "bool", False,
         "Live telemetry: the process-wide metrics registry (scheduler "
         "depth/wait, memory, spill tiers, compile cache, shuffle data "
         "plane, per-op throughput), the /metrics + /healthz surface "
         "(HTTP and the service-protocol stats/health ops), and the "
         "incident flight recorder. Off (default) spawns zero threads "
         "and keeps every hot-path hook at one module-global check "
         "(scripts/telemetry_matrix.sh gates it).")
register("spark.rapids.tpu.telemetry.http.port", "int", -1,
         "Port for the stdlib HTTP scrape thread serving /metrics "
         "(Prometheus text) and /healthz (JSON). -1 (default) disables "
         "the HTTP thread entirely — socket-only deployments use the "
         "service-protocol stats/health ops instead; 0 binds an "
         "ephemeral port (tests read it back).")
register("spark.rapids.tpu.telemetry.http.host", "string", "127.0.0.1",
         "Bind address for the telemetry HTTP thread.")
register("spark.rapids.tpu.telemetry.labels.maxCardinality", "int", 64,
         "Max distinct label sets per metric family; further label "
         "values collapse into the '__overflow__' series (totals stay "
         "exact, attribution coarsens) so no label feed can grow the "
         "registry without bound.")
register("spark.rapids.tpu.telemetry.flightRecorder.capacity", "int", 2048,
         "Events held in the incident flight-recorder ring (the most "
         "recent N engine events dumped when a query dies terminally).")
register("spark.rapids.tpu.telemetry.flightRecorder.dir", "string", "",
         "Directory for incident dumps (schema-validated JSONL, one "
         "'incident' header + the ring's 'event' records). Empty falls "
         "back to spark.rapids.tpu.metrics.eventLog.dir; with neither "
         "set, dumps are disabled (the ring still records).")
register("spark.rapids.tpu.telemetry.flightRecorder.rejectStormThreshold",
         "int", 8,
         "Admission rejections within rejectStormWindowSec that count as "
         "a storm and trigger an incident dump (shed queries die without "
         "profiles; the storm dump is their evidence).")
register("spark.rapids.tpu.telemetry.flightRecorder.rejectStormWindowSec",
         "double", 10.0,
         "Sliding window for rejection-storm detection.")
register("spark.rapids.sql.castFloatToString.enabled", "bool", True,
         "Enable float->string cast (Spark-format float printing on host path).")
register("spark.rapids.sql.castStringToFloat.enabled", "bool", True,
         "Enable string->float cast.")
register("spark.rapids.sql.improvedFloatOps.enabled", "bool", True,
         "Allow float ops whose results may differ from CPU Spark in ULPs.")
register("spark.rapids.sql.variableFloatAgg.enabled", "bool", True,
         "Allow float aggregation (non-deterministic ordering => non-bit-identical sums).")
register("spark.rapids.sql.hasNans", "bool", True,
         "Assume float data may contain NaNs (affects agg/join support).")
register("spark.rapids.sql.ansi.enabled", "bool", False,
         "ANSI mode: overflow/invalid-cast raise instead of null/wrap.")
register("spark.sql.ansi.enabled", "bool", False,
         "Host Spark's ANSI switch (honored like the rapids-namespace key).")
register("spark.rapids.sql.tieredProject.enabled", "bool", True,
         "Evaluate projection as tiers of common subexpressions.")
register("spark.rapids.sql.stableSort.enabled", "bool", True,
         "Use stable device sort (required for Spark-identical ordering ties).")
register("spark.rapids.sql.test.enabled", "bool", False,
         "Strict test mode: any CPU fallback in a converted plan raises.")
register("spark.rapids.sql.test.injectRetryOOM", "int", 0,
         "Fault injection: force a RetryOOM on the Nth tracked device allocation "
         "(reference RapidsConf.scala:1250).", internal=True)
register("spark.rapids.sql.test.injectSplitAndRetryOOM", "int", 0,
         "Fault injection: force a SplitAndRetryOOM on the Nth tracked allocation.",
         internal=True)
register("spark.rapids.tpu.test.faults", "string", "",
         "Fault-injection rule specs, ';'-separated `point:kind,k=v...` "
         "(see faults.py for the point catalog and grammar). Installed by "
         "TpuSession.initialize_device; empty disables injection.",
         internal=True)
register("spark.rapids.tpu.test.faults.seed", "int", 42,
         "Seed for probabilistic fault-injection rules, so fault schedules "
         "are reproducible.", internal=True)

# Memory runtime --------------------------------------------------------------------
register("spark.rapids.memory.gpu.allocFraction", "double", 0.9,
         "Fraction of per-chip HBM given to the arena budget "
         "(reference GpuDeviceManager.computeRmmPoolSize).")
register("spark.rapids.memory.gpu.minAllocFraction", "double", 0.25,
         "Minimum HBM fraction; startup fails below this.")
register("spark.rapids.memory.gpu.maxAllocFraction", "double", 1.0,
         "Maximum HBM fraction allowed.")
register("spark.rapids.memory.gpu.reserve", "bytes", 640 << 20,
         "HBM held back from the arena for XLA scratch/fragmentation.")
register("spark.rapids.memory.spill.compression.codec", "string", "zstd",
         "Codec for host-spilled device batches (TableCompressionCodec "
         "analog): none, zstd, or lz4xla (needs the native runtime). Host "
         "accounting uses the compressed size.",
         check_values=("none", "zstd", "lz4xla"))
register("spark.rapids.memory.host.spillStorageSize", "bytes", 1 << 30,
         "Host-RAM spill store capacity before overflowing to disk.")
register("spark.rapids.memory.host.pageablePool.enabled", "bool", True,
         "Allow pageable host fallback when the pinned staging pool is exhausted.")
register("spark.rapids.memory.pinnedPool.size", "bytes", 0,
         "Pinned host staging pool for device transfers (0 = disabled).")
register("spark.rapids.memory.gpu.oomDumpDir", "string", "",
         "If set, dump allocator state to this dir on unrecoverable OOM.")
register("spark.rapids.memory.gpu.state.debug", "string", "",
         "Log allocator state on OOM: stdout/stderr/path.", internal=True)

# Shuffle ---------------------------------------------------------------------------
register("spark.rapids.shuffle.hostStoreSize", "bytes", 1 << 30,
         "Host-memory budget for the MULTITHREADED shuffle block store; "
         "blocks beyond it overflow (FIFO) to files under "
         "spark.rapids.shuffle.spillPath (RapidsDiskBlockManager analog) "
         "so a shuffle larger than host RAM completes.")
register("spark.rapids.shuffle.spillPath", "string", "",
         "Directory for overflowed shuffle blocks (empty = a fresh temp "
         "dir per manager).")
register("spark.rapids.shuffle.mode", "string", "MULTITHREADED",
         "MULTITHREADED: host-serialized threaded shuffle (reference default); "
         "ICI: device-resident collective all-to-all exchange over the mesh "
         "(UCX-mode analog); CACHE_ONLY: device-resident local-only cache.",
         check_values=("MULTITHREADED", "ICI", "CACHE_ONLY"))
register("spark.rapids.shuffle.multiThreaded.writer.threads", "int", 4,
         "Threads parallelizing shuffle serialization/compression/IO on write.")
register("spark.rapids.shuffle.multiThreaded.reader.threads", "int", 4,
         "Threads parallelizing shuffle fetch/decompression on read.")
register("spark.rapids.shuffle.compression.codec", "string", "zstd",
         "Batch compression codec for shuffle buffers: none, zstd, lz4xla (native).",
         check_values=("none", "zstd", "lz4xla"))
register("spark.rapids.shuffle.checksum.enabled", "bool", True,
         "Frame every shuffle block with a CRC32C over its payload, verified "
         "on fetch; a corrupt frame raises ShuffleCorruptionError and is "
         "refetched once before failing the task.")
register("spark.rapids.shuffle.fetch.maxRetries", "int", 3,
         "Retries per peer for a failed remote shuffle fetch (exponential "
         "backoff between attempts) before failing over to another live "
         "peer or raising ShuffleFetchFailedError.")
register("spark.rapids.shuffle.fetch.retryWaitMs", "int", 10,
         "Base backoff between shuffle fetch retries; attempt k waits "
         "2^k times this (capped at 1s).")
register("spark.rapids.shuffle.ici.chunkBytes", "bytes", 64 << 20,
         "Per-step all-to-all chunk size over ICI.")
register("spark.rapids.shuffle.ici.slotRows", "int", 0,
         "Per-destination slot rows for the ICI all-to-all (0 = auto: the "
         "per-device capacity, which can never overflow). Smaller values bound "
         "skew memory; overflow is detected on device and retried larger.")

register("spark.rapids.sql.join.subPartition.rows", "int", 4 << 20,
         "Build sides larger than this hash-split into key-aligned "
         "sub-partitions joined pairwise (GpuSubPartitionHashJoin analog).")

register("spark.rapids.sql.autoBroadcastJoinThreshold", "int", 10 << 20,
         "Build sides estimated at or below this many bytes join via a "
         "host-serialized broadcast exchange (GpuBroadcastExchangeExec "
         "analog) instead of a shuffled join; -1 disables broadcast joins.")

# I/O -------------------------------------------------------------------------------
register("spark.rapids.sql.format.parquet.enabled", "bool", True,
         "Enable TPU parquet scan/write.")
register("spark.rapids.sql.format.parquet.deviceWrite.enabled", "bool", True,
         "Encode parquet writes on device (PLAIN pages; value compaction + "
         "byte marshalling run on TPU, host writes thrift framing). Falls "
         "back to the host writer for strings/nested/partitioned writes.")
register("spark.rapids.sql.format.parquet.reader.type", "string", "AUTO",
         "Reader strategy: AUTO, PERFILE, COALESCING, MULTITHREADED "
         "(reference GpuParquetScan three strategies).",
         check_values=("AUTO", "PERFILE", "COALESCING", "MULTITHREADED"))
register("spark.rapids.sql.format.parquet.multiThreadedRead.numThreads", "int", 20,
         "Global multi-file reader pool size (reference MultiFileReaderThreadPool).")
register("spark.rapids.sql.format.parquet.multiThreadedRead.maxNumFilesParallel", "int",
         2147483647, "Max files fetched in parallel per task.")
register("spark.rapids.sql.format.csv.deviceDecode.enabled", "bool", True,
         "Parse unquoted CSV on device: host frames line boundaries, the "
         "device gathers rows into the byte matrix, splits fields, and "
         "types them through the device cast kernels "
         "(GpuTextBasedPartitionReader analog). Quoted files and "
         "unsupported shapes keep the host reader.")
register("spark.rapids.sql.format.parquet.deviceDecode.enabled", "bool", True,
         "Decode PLAIN-encoded flat numeric parquet pages on device (RLE "
         "def-level expansion + byte bitcast); unsupported chunks fall back "
         "to the pyarrow host path per file.")
register("spark.rapids.sql.format.orc.deviceWrite.enabled", "bool", True,
         "Encode ORC on device (GpuOrcFileFormat analog): PRESENT bitmaps, "
         "RLEv2 DIRECT integer/length runs, IEEE754 lanes and string "
         "blobs render with device kernels; the host writes protobuf "
         "scaffolding only. Unsupported schemas keep the pyarrow writer.")
register("spark.rapids.sql.format.csv.deviceWrite.enabled", "bool", True,
         "Format CSV on device: columns render through the cast-to-string "
         "kernels, rows assemble and flatten with positional gathers, one "
         "D2H ships the finished blob. Cells needing quoting and float "
         "columns keep the host writer.")
register("spark.rapids.delta.checkpointInterval", "int", 10,
         "Write a parquet checkpoint + _last_checkpoint pointer every Nth "
         "Delta commit so log replay is O(commits since checkpoint); 0 "
         "disables periodic checkpointing.")
register("spark.rapids.sql.format.json.deviceDecode.enabled", "bool", True,
         "Parse flat json-lines on device: host frames lines and proves "
         "flatness (no escapes/arrays/nesting) with one vectorized quote-"
         "parity pass, the device splits fields on structural commas, "
         "matches keys to schema names order-independently, and types the "
         "value spans through the device cast kernels (GPU JSON reader "
         "analog). Unsupported files keep the pyarrow host reader.")
register("spark.rapids.sql.format.hiveText.deviceDecode.enabled", "bool",
         True,
         "Parse Hive delimited text on device with LazySimpleSerDe "
         "semantics: \\x01 field splits, \\N nulls, blank lines as rows, "
         "short rows null-padded — the device CSV parse parameterized for "
         "the serde (GpuHiveTableScanExec analog).")
register("spark.rapids.sql.format.orc.enabled", "bool", True, "Enable TPU ORC scan.")
register("spark.rapids.sql.format.orc.deviceDecode.enabled", "bool", True,
         "Decode flat ORC stripes on device: RLEv2 runs expand via "
         "searchsorted run tables with big-endian bit-window unpacking, "
         "present streams bit-unpack msb-first, strings gather from the "
         "stripe blob (GpuOrcScan analog). Unsupported stripes fall back "
         "to the pyarrow host path per stripe.")
register("spark.rapids.sql.format.csv.enabled", "bool", True, "Enable TPU CSV scan.")
register("spark.rapids.sql.format.json.enabled", "bool", True, "Enable TPU JSON scan.")
register("spark.rapids.sql.format.iceberg.enabled", "bool", True,
         "Enable iceberg table scans (metadata walked natively, data files "
         "ride the TPU parquet scan; row-level deletes unsupported).")
register("spark.rapids.sql.format.avro.enabled", "bool", True,
         "Enable TPU Avro scan (built-in host object-container-file decoder, "
         "io/avro.py; null + deflate codecs).")
register("spark.rapids.cloudSchemes", "string", "s3,s3a,s3n,wasbs,gs,abfs,abfss",
         "URI schemes treated as cloud stores; selects MULTITHREADED reader under AUTO.")

# Planning --------------------------------------------------------------------------
register("spark.rapids.sql.adaptive.enabled", "bool", False,
         "AQE analog: materialize each exchange stage, observe its row count, "
         "and re-run the override planning (and CBO) on the remaining plan.")
register("spark.rapids.sql.adaptive.coalescePartitions.enabled", "bool", True,
         "Under AQE, shrink a staged exchange's partition count toward "
         "advisoryPartitionSizeInBytes using the OBSERVED stage size "
         "(Spark's post-shuffle partition coalescing).")
register("spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes", "bytes",
         64 << 20,
         "Target size of one post-shuffle partition for AQE coalescing and "
         "skew-join splitting.")
register("spark.rapids.sql.adaptive.skewJoin.enabled", "bool", True,
         "Under AQE, split a skewed probe-side hash partition of a staged "
         "join into chunks joined pairwise against the matching build "
         "partition (Spark's OptimizeSkewedJoin).")
register("spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor",
         "double", 5.0,
         "A partition is skewed when its rows exceed this multiple of the "
         "median partition's rows (and the row threshold).")
register("spark.rapids.sql.adaptive.skewJoin.skewedPartitionRowThreshold",
         "int", 100_000,
         "Minimum rows before a partition can be considered skewed.")
register("spark.rapids.sql.optimizer.enabled", "bool", False,
         "Cost-based optimizer: may move plan sections back to CPU to avoid "
         "transition thrash (reference CostBasedOptimizer).")
register("spark.rapids.sql.optimizer.cpuExecCost", "double", 1.0,
         "Relative per-row CPU operator cost.", internal=True)
register("spark.rapids.sql.optimizer.gpuExecCost", "double", 0.3,
         "Relative per-row TPU operator cost.", internal=True)
register("spark.rapids.sql.optimizer.transitionCost", "double", 10.0,
         "Relative per-row cost of a CPU<->TPU transition.", internal=True)
register("spark.rapids.sql.incompatibleOps.enabled", "bool", True,
         "Allow ops marked incompat (minor semantic differences) on TPU.")
register("spark.rapids.sql.incompatibleDateFormats.enabled", "bool", False,
         "Allow date formats with known corner-case differences.")
register("spark.rapids.sql.regexp.enabled", "bool", True,
         "Enable regular-expression offload via the transpiler (falls back per-pattern).")

# TPU-specific ----------------------------------------------------------------------
register("spark.rapids.sql.dynamicFilePruning.enabled", "bool", True,
         "Prune probe-side parquet files/row groups of a broadcast hash "
         "join using the build side's distinct keys against footer min/max "
         "statistics (the GpuSubqueryBroadcastExec / dynamic partition "
         "pruning analog at file granularity).")
register("spark.rapids.sql.topK.enabled", "bool", True,
         "Rewrite limit-over-sort into a top-k exec (per-batch k-select + "
         "running merge) instead of a full out-of-core sort "
         "(TakeOrderedAndProjectExec analog, GpuOverrides.scala:3705).")
register("spark.rapids.sql.topK.threshold", "int", 10000,
         "Largest LIMIT+OFFSET rewritten into the top-k exec (the "
         "spark.sql.execution.topKSortFallbackThreshold analog). Above it "
         "the planner keeps sort+limit: top-k holds an O(k) candidate "
         "batch device-resident and re-sorts ~2k rows per input batch, "
         "losing the out-of-core sort's spill behavior at large k.")
register("spark.rapids.tpu.string.headWidth", "int", 256,
         "Head width (bytes) of the chunked long-string device layout: "
         "strings longer than this keep their first headWidth bytes in the "
         "rectangular byte matrix and the rest in a shared tail blob with "
         "per-row (offset) spans, so ONE long value no longer widens the "
         "whole column to cap x width (the libcudf offset+data strings "
         "analog). Byte-inspecting kernels on such columns fall back per "
         "op; row-moving ops (filter/join/sort gathers) stay on device.")
register("spark.rapids.tpu.device.ordinal", "int", -1,
         "Which local TPU device to bind (-1 = first).", startup_only=True)
register("spark.rapids.tpu.device.startupTimeoutSec", "double", 60.0,
         "Deadline (seconds) for the FIRST backend touch (device enumeration / "
         "client init). A wedged device runtime raises DeviceStartupError with "
         "diagnostics instead of hanging the query indefinitely (the reference "
         "inspects and fail-fasts executor startup, Plugin.scala:436-459). "
         "<= 0 disables the guard.", startup_only=True)
register("spark.rapids.tpu.padding.minRows", "int", 128,
         "Minimum padded row bucket (lane-aligned).")
register("spark.rapids.tpu.padding.growth", "double", 2.0,
         "Row bucket growth factor (powers of this between min and max).")
register("spark.rapids.tpu.string.maxWidth", "int", 8192,
         "Max per-batch string width for the fixed-width byte-matrix layout; longer "
         "strings fall the batch back to host processing.")
register("spark.rapids.tpu.f64.emulation", "bool", True,
         "Keep float64 math exact (XLA f64 on TPU); if false, DOUBLE computes as f32.")
register("spark.rapids.tpu.mesh.shape", "string", "",
         "Logical device mesh as 'name=N,name=M' (empty = single device).",
         startup_only=True)

# Sharded execution over the ICI mesh (spark_rapids_tpu/mesh/) ------------------------
register("spark.rapids.tpu.mesh.enabled", "bool", False,
         "Sharded execution subsystem (mesh/): with an active "
         "spark.rapids.tpu.mesh.shape and spark.rapids.shuffle.mode=ICI, a "
         "plan pass partitions file/in-memory scans across mesh positions "
         "(row-group/file/row ranges per chip, riding the existing io/ "
         "decoders per shard), resizes safe hash-exchange boundaries to the "
         "mesh, and keeps post-exchange partitions resident on their own "
         "device between pipeline stages (zero-copy per-chip shard handoff "
         "instead of a host-side concat between exchange and join/agg). Off "
         "(default): one conf read per plan, zero mesh modules imported, "
         "byte-identical plans and results.")
register("spark.rapids.tpu.mesh.resizeExchanges", "bool", True,
         "With mesh execution enabled, rewrite plan-level HASH exchange "
         "boundaries whose partition count differs from the mesh size to "
         "mesh-sized exchanges so they ride the ICI collective (partition "
         "count of an internal hash exchange is an engine knob, like AQE "
         "coalescing). Round-robin/range/single specs are never resized — "
         "a mismatched count degrades that exchange to the host data plane "
         "(never a wrong split).")
register("spark.rapids.tpu.mesh.scan.parallel", "bool", False,
         "Decode mesh scan shards on concurrent worker threads (one per "
         "shard). Workers adopt the query's ONE admission hold (the single "
         "mesh-wide door) — they never take per-chip tokens of their own — "
         "and park finished shards as budget-visible, chip-tagged "
         "spillables until the consumer drains them in mesh order.")
register("spark.rapids.tpu.mesh.hbmPerChip", "bytes", 0,
         "Per-chip HBM sub-budget for mesh-resident shard buffers (0 "
         "disables per-chip accounting). Chip-tagged parked buffers charge "
         "their OWN chip's ledger; overflowing one chip spills only that "
         "chip's buffers — a shard spilling on chip 3 never charges or "
         "evicts chip 0.")

# Pipelined execution ----------------------------------------------------------------
register("spark.rapids.tpu.pipeline.enabled", "bool", True,
         "Pipelined execution: bounded-depth background prefetch of "
         "upstream batches at the scan, coalesce-input and result-sink "
         "seams (host-side work overlaps device execution) plus the "
         "fused multi-chunk scan decode. Off restores the strictly "
         "serial pre-pipeline paths — zero prefetch threads, one decode "
         "dispatch group per row-group chunk.")
register("spark.rapids.tpu.pipeline.prefetch.depth", "int", 2,
         "Max batches a pipeline prefetch thread may run ahead of its "
         "consumer. Prefetched batches are parked as spillable (budget-"
         "visible, spillable under pressure) until the consumer "
         "materializes them, so depth bounds device residency, not just "
         "queue length.")
register("spark.rapids.tpu.pipeline.scan.chunksPerDispatch", "int", 4,
         "Row-group chunks the device parquet scan decodes per fused "
         "dispatch: their control-plane arrays pack into ONE host "
         "buffer, ship in ONE transfer, and expand in ONE compiled "
         "program that emits one merged batch — O(1) dispatches per "
         "scan batch instead of O(columns x chunks). 1 disables chunk "
         "batching (per-row-group decode, the pre-pipeline unit); "
         "ignored when spark.rapids.tpu.pipeline.enabled is false.")

# Scan pushdown ----------------------------------------------------------------------
register("spark.rapids.tpu.scan.pushdown.enabled", "bool", False,
         "Compute on compressed data: fuse supported filter predicates, "
         "pure column projections and global count/min/max/sum aggregates "
         "from the plan into the file scan. The device parquet decode "
         "evaluates pushed predicates directly on dictionary values and "
         "RLE-expanded indices inside the fused multi-chunk program and "
         "late-materializes only surviving rows of projected columns "
         "(aggregate-only queries materialize no row data at all); every "
         "other decode path applies the same predicate/projection exactly "
         "on the decoded batch before emitting. Off (default) leaves "
         "plans byte-identical to the non-pushdown planner with zero "
         "extra state.")
register("spark.rapids.tpu.scan.pushdown.aggregate.enabled", "bool", True,
         "Allow pushing global (non-grouped) count/min/max/sum "
         "aggregates over scan columns into the scan as per-dispatch "
         "partial values merged by a rewritten upstream aggregate. "
         "Integral/date/timestamp/boolean min/max and integral sums "
         "only (exact, order-independent merges); disabled automatically "
         "under ANSI mode. Ignored unless "
         "spark.rapids.tpu.scan.pushdown.enabled is on.")
register("spark.rapids.tpu.scan.pushdown.rowgroup.enabled", "bool", True,
         "Prune whole parquet row groups on the device decode path by "
         "testing the pushed predicate against footer min/max/null-count "
         "statistics before any page bytes are read (conservative: a row "
         "group is skipped only when provably no row can match). Counted "
         "on tpu_scan_rowgroups_pruned_total. Ignored unless "
         "spark.rapids.tpu.scan.pushdown.enabled is on.")

# Whole-stage fusion -----------------------------------------------------------------
register("spark.rapids.tpu.fusion.enabled", "bool", False,
         "Whole-stage fusion: a planner pass (plan/fusion.py) replaces "
         "maximal chains of batch-shape-compatible operators — "
         "expression-only project/filter, broadcast hash-join probe "
         "(inner/left/semi/anti/existence, non-dpp, non-zip), and a "
         "stage-terminal partial hash aggregate — with one fused stage "
         "that compiles through the compile service as a SINGLE device "
         "program: one dispatch per stage per batch, member "
         "intermediates never materialise as ColumnarBatches. Sorts, "
         "windows, exchanges, UDFs, right/full joins and chains under "
         "mesh-resident exchanges break the chain and run unfused. Off "
         "(default) never imports the fusion modules and leaves plans "
         "and results byte-identical to the per-operator paths.")
register("spark.rapids.tpu.fusion.minOps", "int", 2,
         "Minimum member count for a chain to be worth fusing (clamped "
         "to >= 2): shorter chains keep the per-operator kernels, whose "
         "compile cache is warmer across queries. Ignored unless "
         "spark.rapids.tpu.fusion.enabled is on.")
register("spark.rapids.tpu.fusion.pallas.mode", "string", "auto",
         "Backend for the fused stage's hot inner loops (hash-probe "
         "sizing, group-by accumulate): auto uses the hand-written "
         "Pallas kernels (ops/pallas_probe.py, ops/pallas_groupby.py) "
         "on TPU backends and the stock jit lowerings elsewhere; off "
         "forces the jit lowerings everywhere; force runs the Pallas "
         "kernels in interpret mode off-TPU (testing). Both paths are "
         "bit-identical by construction.",
         check_values=("auto", "off", "force"))

# Query scheduler --------------------------------------------------------------------
register("spark.rapids.tpu.sched.enabled", "bool", False,
         "Query scheduler: route device admission (TpuSemaphore and the "
         "device-service token pool) through the priority-weighted fair "
         "admission queue (sched/) with load shedding, per-tenant "
         "weights, deadlines and cooperative cancellation. Off keeps the "
         "exact FIFO paths: a bare BoundedSemaphore in process, FIFO "
         "token grants in the service, zero scheduler state.")
register("spark.rapids.tpu.sched.priority", "int", 0,
         "Default priority for this session's queries (higher = admitted "
         "first under contention; strict priority across levels). "
         "Per-query contexts and the service run_plan header override it.")
register("spark.rapids.tpu.sched.tenant", "string", "default",
         "Tenant id this session's queries are accounted under (fair-"
         "share weights, memory sub-quotas).")
register("spark.rapids.tpu.sched.deadlineMs", "int", 0,
         "Default per-query deadline. A query running (or queued, or "
         "sleeping in a retry backoff) past it unwinds with the typed "
         "DeadlineExceededError; 0 = no deadline.")
register("spark.rapids.tpu.sched.maxQueueDepth", "int", 0,
         "Admission load shedding: a query arriving when this many are "
         "already queued is rejected immediately with QueryRejectedError "
         "(it never touches the device); 0 = unbounded queue.")
register("spark.rapids.tpu.sched.maxQueueWaitMs", "int", 0,
         "Admission load shedding: a query queued longer than this is "
         "rejected in place with QueryRejectedError; 0 = unbounded wait.")
register("spark.rapids.tpu.sched.tenant.weights", "string", "",
         "Per-tenant fair-share weights as 'tenantA=4,tenantB=1' (unlisted "
         "tenants weigh 1). Within a priority level, admission grants are "
         "proportional to weight under sustained contention (stride "
         "scheduling over a per-tenant virtual pass).")
register("spark.rapids.tpu.sched.tenant.quotas", "string", "",
         "Per-tenant device-memory sub-quotas as fractions of the budget, "
         "'tenantA=0.5,tenantB=0.25'. The quota is a hard sub-limit: a "
         "tenant reserving beyond it gets SplitAndRetryOOM immediately — "
         "no spill, since spilling frees other tenants' buffers without "
         "shrinking this tenant's pinned ledger — even while the global "
         "budget has room, so one tenant's out-of-core sort splits down "
         "to its share instead of evicting another tenant's working set. "
         "Empty = no sub-quotas (global budget only).")

# Result & fragment cache ------------------------------------------------------------
register("spark.rapids.tpu.rescache.enabled", "bool", False,
         "Result & fragment cache: transparently reuse materialized "
         "columnar fragments (scan output, shuffle-exchange output, "
         "broadcast payloads) and whole-query results across queries, "
         "keyed by a canonical plan fingerprint (exec tree + bound-"
         "expression reprs + output schema + source-file identity + "
         "result-affecting confs). A whole-query hit answers without "
         "touching the device (no admission token). Off (default) keeps "
         "every execution path byte-for-byte pre-cache: zero threads, "
         "zero state (scripts/rescache_matrix.sh gates it).")
register("spark.rapids.tpu.rescache.maxBytes", "bytes", 512 << 20,
         "Cache capacity across all entries (device fragments count "
         "their batch bytes, host results/blobs their host bytes). "
         "Inserting past it evicts by cost-aware LRU: lowest "
         "(recompute-time x (1+hits)) / bytes goes first, so cheap-to-"
         "recompute bulk leaves before expensive small results. Device "
         "fragments additionally ride the spill catalog's device->host->"
         "disk tiers under memory pressure, independent of this cap.")
register("spark.rapids.tpu.rescache.query.enabled", "bool", True,
         "Cache whole-query results (TpuSession.execute_plan seam). A "
         "hit takes the fast path: the reply is served from the host "
         "copy without device admission.")
register("spark.rapids.tpu.rescache.scan.enabled", "bool", True,
         "Cache file-scan output fragments (TpuFileScanExec seam), "
         "keyed by (path, mtime, size) per file so a rewritten source "
         "recomputes. Scans carrying runtime dynamic-pruning filters "
         "are never cached (their output depends on the join's build "
         "keys).")
register("spark.rapids.tpu.rescache.exchange.enabled", "bool", True,
         "Cache shuffle-exchange output fragments (TpuShuffleExchange"
         "Exec seam; local shuffle modes only — ICI mesh exchanges "
         "produce sharded arrays the spill catalog cannot own).")
register("spark.rapids.tpu.rescache.broadcast.enabled", "bool", True,
         "Cache broadcast payload blobs (TpuBroadcastExchangeExec "
         "seam): the host-serialized build side is reused across "
         "queries, skipping child re-execution and re-serialization.")
register("spark.rapids.tpu.rescache.minRecomputeMs", "double", 0.0,
         "Only store a fragment/result whose recompute cost was at "
         "least this many milliseconds — keeps trivially cheap "
         "fragments from churning the capacity. 0 stores everything.")
register("spark.rapids.tpu.rescache.persist.dir", "string", "",
         "Directory for the persistent whole-query result tier "
         "(CRC32C-framed Arrow blobs, compile-cache discipline: a torn "
         "or poisoned entry is a miss + delete, never a wrong result). "
         "Only entries whose fingerprints carry pure file/delta "
         "identity (no in-memory table ids) persist; staleness is "
         "inside the fingerprint (file mtime/size, delta version), so "
         "rewritten sources miss naturally. A restarted worker answers "
         "previously-hot fingerprints from this tier with zero device "
         "admissions. IO failures degrade the tier to memory-only "
         "(typed PersistenceDegradedWarning + telemetry counter + "
         "flight-recorder incident) — never a failed query. Empty "
         "disables persistence; the in-memory cache still runs.")
register("spark.rapids.tpu.rescache.persist.maxBytes", "bytes", 1 << 30,
         "Capacity of the persistent result tier's directory; storing "
         "past it deletes oldest entries (file mtime) first. One entry "
         "larger than the whole budget is never persisted.")
register("spark.rapids.tpu.rescache.persist.warmup.enabled", "bool", True,
         "Background-reload every persisted result into the in-memory "
         "cache at device init (one `rescache-warmup` thread), so the "
         "first post-restart dashboard hit needs no disk read. Off, "
         "persisted entries still serve lazily on first lookup.")

# Runtime statistics -----------------------------------------------------------------
register("spark.rapids.tpu.stats.enabled", "bool", False,
         "Runtime query statistics: a per-query observer derives per-"
         "operator actuals (output rows/batches, filter selectivity, "
         "join build size and fan-out, per-partition exchange bytes) "
         "from the existing metrics seams, pairs each with the CBO's "
         "plan-time estimate (q-error), and records actuals into a "
         "cardinality history keyed by canonical subplan fingerprints. "
         "Enables TpuSession.explain_analyze() and the profile_report "
         "--stats section. Off (default) creates zero state, spawns "
         "zero threads, and leaves planning byte-identical "
         "(scripts/stats_matrix.sh gates it).")
register("spark.rapids.tpu.stats.feedback.enabled", "bool", False,
         "Optimizer feedback from the statistics history: "
         "cbo.row_estimate / filter selectivity consult observed "
         "actuals before falling back to heuristics (broadcast-vs-"
         "shuffle decisions track real build sizes), and adaptive "
         "execution picks post-shuffle coalesce counts and pre-flags "
         "skewed joins from historical stage sizes without first "
         "staging. Requires spark.rapids.tpu.stats.enabled; off keeps "
         "estimates byte-identical to the static heuristics.")
register("spark.rapids.tpu.stats.history.maxEntries", "int", 4096,
         "In-memory LRU capacity of the cardinality history (one entry "
         "per fingerprinted subtree).")
register("spark.rapids.tpu.stats.history.dir", "string", "",
         "Directory for the persistent statistics tier (CRC32C-framed "
         "JSONL, one record per line; a torn or corrupt line is a miss, "
         "never a wrong stat) so a restarted worker keeps its learned "
         "cardinalities. Only fingerprints without process-local "
         "identity (no in-memory table ids) persist. Empty disables "
         "persistence; the in-memory tier still runs.")
register("spark.rapids.tpu.stats.misestimate.incidentThreshold", "double",
         100.0,
         "q-error at or above which the worst misestimate of a query "
         "dumps a flight-recorder incident (reason 'misestimate') — "
         "evidence for plans that ran with catastrophically wrong "
         "cardinalities. 0 disables the incident hook.")

# Live query introspection -----------------------------------------------------------
register("spark.rapids.tpu.live.enabled", "bool", False,
         "Live query introspection: a per-process registry of in-flight "
         "queries (tenant, trace id, current operator, per-operator "
         "rows/batches sampled from the existing metrics seams) with "
         "progress/ETA estimated against the runtime-statistics history, "
         "a slow-query watchdog thread, and exposure on /queries (HTTP), "
         "the `queries` service op, the fleet-gateway fan-out, and the "
         "tpu_live_* telemetry gauges. Off (default) spawns zero "
         "threads, creates zero state, and keeps every hook at one "
         "module-global check (scripts/liveview_matrix.sh gates it). "
         "Progress fractions and ETAs need spark.rapids.tpu.stats."
         "enabled so fingerprint history exists; without it queries "
         "report rows-only progress.")
register("spark.rapids.tpu.live.slowFactor", "double", 3.0,
         "A query running longer than this multiple of its HISTORICAL "
         "wall time (same statistics-history fingerprint) is flagged by "
         "the watchdog as a flight-recorder `slow_query` incident "
         "carrying the live operator snapshot. Queries with no history "
         "are never flagged (fail-closed, no false positives).")
register("spark.rapids.tpu.live.watchdog.intervalMs", "int", 500,
         "Slow-query watchdog scan cadence over the in-flight registry.")
register("spark.rapids.tpu.live.watchdog.cancel", "bool", False,
         "Let the watchdog CANCEL a flagged slow query through its "
         "CancelToken (the engine unwinds with the typed "
         "QueryCancelledError at its next cooperative checkpoint). Off "
         "(default) only flags and raises the incident.")
register("spark.rapids.tpu.live.debugSignal", "bool", False,
         "Install a SIGUSR2 handler that dumps the flight-recorder ring "
         "plus the live query registry as a schema-valid JSONL incident "
         "(reason `debug_signal`) — a wedged process becomes debuggable "
         "without killing it. Requires the main thread to run "
         "initialize_device.")
register("spark.rapids.tpu.live.recentQueries", "int", 32,
         "Recently finished queries kept (terminal snapshots) in the "
         "live registry's ring for the /queries `recent` section.")

# Compile service --------------------------------------------------------------------
register("spark.rapids.tpu.compile.enabled", "bool", True,
         "Route every kernel compile through the centralized compile "
         "service (keyed program cache + single-flight dedup + compile "
         "accounting). Off = direct per-call-site jax.jit, no caching "
         "policy or metrics.")
register("spark.rapids.tpu.compile.cache.maxPrograms", "int", 512,
         "In-memory LRU capacity of the compile service's program cache "
         "(one entry per op x static-args x input-shape signature).")
register("spark.rapids.tpu.compile.cache.dir", "string", "",
         "Directory for the persistent compile-cache tier (serialized "
         "programs, CRC32C-framed; a corrupt entry is a miss + delete). "
         "Empty disables persistence; the in-memory tier still runs.")
register("spark.rapids.tpu.compile.warmup.enabled", "bool", False,
         "Precompile hot operator programs on a background thread at "
         "device init: preload every persistent-tier entry, then compile "
         "the generic row-movement kernels over warmup.schema x the "
         "padding bucket ladder, so the first query hits warm "
         "executables.")
register("spark.rapids.tpu.compile.warmup.ops", "string",
         "concat,sortpos,slice",
         "Synthetic warmup kernel families: concat (coalesce/exchange "
         "batch concat), sortpos (out-of-core merge position sort), "
         "slice (partition slice).")
register("spark.rapids.tpu.compile.warmup.schema", "string", "long,double",
         "Schema template for synthetic warmup batches (csv of "
         "long,int,double,float,bool,string).")
register("spark.rapids.tpu.compile.warmup.maxRows", "int", 1 << 20,
         "Top of the padding-bucket ladder the synthetic warmup walks.")
register("spark.rapids.tpu.compile.tuner.enabled", "bool", False,
         "Adaptive bucket tuner auto mode: learn a padding-bucket ladder "
         "from observed batch row counts and re-install it every "
         "tuner.interval observations (observation/manual retune() is "
         "always available; auto mode costs one recompile wave per ladder "
         "change).")
register("spark.rapids.tpu.compile.tuner.maxBuckets", "int", 8,
         "Maximum rungs in the learned bucket ladder.")
register("spark.rapids.tpu.compile.tuner.minSamples", "int", 64,
         "Observations required before the tuner's auto mode may retune.")
register("spark.rapids.tpu.compile.tuner.interval", "int", 256,
         "Auto-mode retune cadence (every N observed batches).")

# ---- fleet gateway (spark_rapids_tpu/fleet/) -----------------------------
register("spark.rapids.tpu.fleet.probe.intervalMs", "int", 1000,
         "Fleet gateway: background health-probe cadence per worker. A "
         "crashed worker trips its circuit breaker within roughly this "
         "interval even with zero query traffic; a restarted one is "
         "re-admitted through the breaker's half-open trial probe.")
register("spark.rapids.tpu.fleet.probe.timeoutSec", "double", 2.0,
         "Fleet gateway: per-probe (and per-dispatch connect) socket "
         "timeout. A worker that accepts but never answers within this "
         "counts as a probe failure.")
register("spark.rapids.tpu.fleet.breaker.failures", "int", 3,
         "Fleet gateway: consecutive probe/dispatch failures that trip a "
         "worker's circuit breaker OPEN (no traffic until the cooldown "
         "elapses and a half-open trial succeeds).")
register("spark.rapids.tpu.fleet.breaker.cooldownMs", "int", 5000,
         "Fleet gateway: how long an OPEN breaker blocks all traffic to "
         "its worker before admitting one half-open trial.")
register("spark.rapids.tpu.fleet.maxOutstanding", "int", 0,
         "Fleet gateway: per-worker cap on concurrently dispatched "
         "queries. When EVERY routable worker is at the cap the gateway "
         "sheds at its own door (typed rejected reply) before touching "
         "any worker socket. 0 = uncapped.")
register("spark.rapids.tpu.fleet.failover.maxAttempts", "int", 3,
         "Fleet gateway: total workers tried per run_plan (first "
         "dispatch + failovers) within the caller's deadline. Write "
         "plans never failover once a request may have started "
         "executing, regardless of this budget.")
register("spark.rapids.tpu.fleet.dispatch.timeoutSec", "double", 600.0,
         "Fleet gateway: upstream wait bound for a dispatched run_plan "
         "when the caller supplied no deadline; expiry counts as a "
         "worker connection failure (wedged worker).")
register("spark.rapids.tpu.fleet.routing", "string", "affinity",
         "Fleet gateway routing policy: 'affinity' (default) rendezvous-"
         "hashes the plan fingerprint to a preferred worker, falling "
         "back to power-of-two-choices load routing for "
         "unfingerprintable plans; 'random' disables affinity entirely "
         "(load-only — the CI/bench baseline that shows what affinity "
         "buys).", check_values=("affinity", "random"))
register("spark.rapids.tpu.fleet.drain.timeoutSec", "double", 30.0,
         "Fleet gateway: upper bound on how long a `drain` op with "
         "wait_s may block for the worker's in-flight queries to "
         "finish.")
register("spark.rapids.tpu.fleet.failoverStorm.threshold", "int", 5,
         "Fleet gateway: failovers within failoverStorm.windowSec that "
         "dump one flight-recorder incident (a flapping worker churning "
         "the pool leaves evidence even though individual queries "
         "succeed).")
register("spark.rapids.tpu.fleet.failoverStorm.windowSec", "double", 10.0,
         "Fleet gateway: sliding window for failover-storm detection; "
         "also the per-window incident rate limit.")
register("spark.rapids.tpu.fleet.supervisor.enabled", "bool", False,
         "Fleet supervisor mode: the gateway process spawns and "
         "SUPERVISES its workers — a crashed worker is respawned at the "
         "same socket address with exponential backoff, the prober's "
         "half-open trial re-admits it, and its persistent tiers "
         "(compile cache, result tier, stats history) bring it back "
         "warm. Off (default), the gateway only routes around dead "
         "workers (external process management owns restarts).")
register("spark.rapids.tpu.fleet.supervisor.maxRestarts", "int", 5,
         "Fleet supervisor: lifetime respawn budget per worker. A "
         "worker crashing past it is marked FAILED (flight-recorder "
         "incident; no further respawns) — a crash loop must page "
         "someone, not burn CPU forever.")
register("spark.rapids.tpu.fleet.supervisor.backoffMs", "int", 200,
         "Fleet supervisor: respawn backoff base; doubles per "
         "consecutive restart up to supervisor.backoffMaxMs.")
register("spark.rapids.tpu.fleet.supervisor.backoffMaxMs", "int", 5000,
         "Fleet supervisor: respawn backoff ceiling.")
register("spark.rapids.tpu.fleet.supervisor.checkIntervalMs", "int", 100,
         "Fleet supervisor: how often the monitor thread polls worker "
         "processes for unexpected exits.")


class TpuConf:
    """Instance view over a settings dict, with typed accessors (reference
    `RapidsConf(conf)` `RapidsConf.scala:1973`)."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings: Dict[str, Any] = dict(settings or {})
        # environment overrides, dots->underscores upper (SPARK_RAPIDS_SQL_ENABLED...)
        for key, entry in _REGISTRY.items():
            env = key.upper().replace(".", "_")
            if env in os.environ and key not in self._settings:
                self._settings[key] = os.environ[env]

    def get(self, key: str) -> Any:
        e = _REGISTRY.get(key)
        if e is None:
            # unregistered keys pass through raw (operator enable keys register lazily)
            return self._settings.get(key)
        if key in self._settings:
            return e.convert(self._settings[key])
        return e.default

    def set(self, key: str, value: Any) -> "TpuConf":
        self._settings[key] = value
        if key.startswith("spark.rapids.tpu.padding."):
            # padding params are memoized on the hot bucket path; drop the
            # memo so the next row_bucket sees the new value
            from .columnar import padding
            padding.invalidate_cache()
        elif key.startswith("spark.rapids.tpu.mesh."):
            # the conf->Mesh memo in parallel/mesh.py must not serve a
            # stale mesh after a mid-session conf change (same conf-
            # generation discipline as the padding memo above). Guarded
            # via sys.modules: if the module was never imported there is
            # no cache to invalidate — and importing jax from a bare
            # conf.set would be absurd
            import sys
            m = sys.modules.get("spark_rapids_tpu.parallel.mesh")
            if m is not None:
                m.invalidate_cache()
        return self

    def get_bool(self, key: str, default: bool = True) -> bool:
        v = self.get(key)
        return default if v is None else _convert(v, "bool")

    # Frequently used typed views ----------------------------------------------------
    @property
    def is_sql_enabled(self) -> bool:
        return self.get("spark.rapids.sql.enabled")

    @property
    def is_test_enabled(self) -> bool:
        return self.get("spark.rapids.sql.test.enabled")

    @property
    def explain(self) -> str:
        return self.get("spark.rapids.sql.explain")

    @property
    def is_ansi(self) -> bool:
        return self.get("spark.rapids.sql.ansi.enabled") or \
            self.get("spark.sql.ansi.enabled")

    @property
    def batch_size_bytes(self) -> int:
        return self.get("spark.rapids.sql.batchSizeBytes")

    @property
    def batch_size_rows(self) -> int:
        return self.get("spark.rapids.sql.batchSizeRows")

    @property
    def concurrent_tpu_tasks(self) -> int:
        return self.get("spark.rapids.sql.concurrentGpuTasks")

    @property
    def shuffle_mode(self) -> str:
        return self.get("spark.rapids.shuffle.mode")

    @property
    def string_max_width(self) -> int:
        return self.get("spark.rapids.tpu.string.maxWidth")

    def is_operator_enabled(self, key: str, incompat: bool = False,
                            disabled_by_default: bool = False) -> bool:
        v = self._settings.get(key)
        if v is not None:
            return _convert(v, "bool")
        if disabled_by_default:
            return False
        if incompat:
            return self.get("spark.rapids.sql.incompatibleOps.enabled")
        return True


_default_conf: Optional[TpuConf] = None


def get_default_conf() -> TpuConf:
    global _default_conf
    if _default_conf is None:
        _default_conf = TpuConf()
    return _default_conf


def generate_docs() -> str:
    """Emit docs/configs.md content (reference RapidsConf.help)."""
    lines: List[str] = [
        "# Configuration\n",
        "All configuration keys, their defaults and meaning. Generated by "
        "`spark_rapids_tpu.config.generate_docs()`.\n",
        "| Key | Default | Meaning |", "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        doc = e.doc.replace("|", "\\|")
        lines.append(f"| `{key}` | {e.default!r} | {doc} |")
    return "\n".join(lines) + "\n"
