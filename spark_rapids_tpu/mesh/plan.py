"""The sharded plan pass — hooked in `Overrides.apply` after the
distribution pass, the same way plan/scan_pushdown.py hooks after convert.

Given a converted device plan under an active mesh (the distribution pass
already wrapped join children in mesh-sized key exchanges and split
grouped aggregates into partial -> exchange -> per-shard final), this
pass:

  1. RESIZES plan-carried hash-exchange boundaries whose partition count
     differs from the mesh to mesh-sized exchanges (the un-gating of the
     ICI path beyond `num_partitions == mesh.size`): an internal hash
     exchange's partition count is an engine knob, exactly like AQE
     coalescing, so `repartition(200, key)` under an 8-chip mesh becomes
     an 8-way ICI collective instead of a host shuffle. Round-robin /
     range / single specs are NEVER resized — a mismatched count there
     degrades that exchange to the host data plane (never a wrong split);

  2. marks each mesh-sized exchange whose consumer is shard-wise (zipped
     join, per-shard final aggregate) for DEVICE-RESIDENT output: its
     partitions are handed downstream as zero-copy per-chip views
     (exec/exchange.py + shard.py shard_view) instead of gathered
     replicated slices;

  3. wraps the scans feeding each mesh exchange (through per-batch-
     preserving operators: filter, project, partial aggregate) in
     `MeshShardedScanExec`, partitioning their input across mesh
     positions so the pipeline is sharded end to end.

Off-path: `Overrides.apply` reads ONE conf bool before importing this
module — mesh off means zero mesh imports and byte-identical plans.
"""

from __future__ import annotations

from typing import List, Optional


def apply_mesh_plan(root, conf, explain_log: Optional[List[str]] = None):
    """Rewrite a converted device plan for sharded mesh execution.
    Returns the (mutated) root; a non-TpuExec root or inactive mesh is
    returned untouched."""
    from ..exec.base import TpuExec
    if not isinstance(root, TpuExec):
        return root
    from ..parallel.mesh import mesh_from_conf
    mesh = mesh_from_conf(conf)
    if mesh is None:
        return root
    import jax
    me = jax.process_index()
    if any(d.process_index != me for d in mesh.devices.flat):
        # multi-host mesh: shard production commits batches with
        # device_put, which requires every mesh device to be addressable
        # from this process. The legacy concat data plane (which the
        # un-sharded plan still takes under ICI mode) handles multi-host;
        # the sharded pass stands down rather than crash at execute.
        if explain_log is not None:
            explain_log.append("mesh: multi-host mesh — sharded plan "
                               "pass skipped (devices not all "
                               "process-addressable)")
        return root
    from . import note_active
    note_active()
    log = explain_log if explain_log is not None else []
    _walk(root, None, conf, mesh.size, log)
    return root


def _walk(node, parent, conf, ndev: int, log: List[str]) -> None:
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.exchange import TpuShuffleExchangeExec
    from ..exec.joins import TpuShuffledHashJoinExec
    from ..plan.nodes import HashPartitionSpec
    for c in list(node.children):
        _walk(c, node, conf, ndev, log)
    if not isinstance(node, TpuShuffleExchangeExec):
        return
    spec = node.spec
    if isinstance(spec, HashPartitionSpec) and \
            spec.num_partitions != ndev and \
            conf.get("spark.rapids.tpu.mesh.resizeExchanges"):
        node.spec = HashPartitionSpec(list(spec.keys), ndev)
        log.append(f"mesh: resized hash exchange "
                   f"{spec.num_partitions} -> {ndev} partitions (ICI)")
        spec = node.spec
    if spec.num_partitions != ndev:
        log.append(f"mesh: exchange stays on the host data plane "
                   f"(num_partitions={spec.num_partitions} != "
                   f"mesh.size={ndev})")
        return
    resident = (isinstance(parent, TpuShuffledHashJoinExec)
                and getattr(parent, "zip_partitions", False)) or \
               (isinstance(parent, TpuHashAggregateExec)
                and parent.mode == "final"
                and getattr(parent, "partitioned_input", False))
    node.mesh_resident_out = bool(resident)
    _shard_scans(node.children[0], node, conf, ndev, log)


# operators that preserve the one-batch-per-shard alignment (1:1 per input
# batch) between a scan and its mesh exchange; coalesce merges batches and
# is deliberately absent
def _shard_scans(node, parent, conf, ndev: int, log: List[str]) -> None:
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.basic import TpuFilterExec, TpuProjectExec, TpuScanExec
    from ..io.scanbase import TpuFileScanExec
    from .shard import MeshShardedScanExec
    if isinstance(node, (TpuFileScanExec, TpuScanExec)):
        wrapper = MeshShardedScanExec(node, conf)
        for i, c in enumerate(parent.children):
            if c is node:
                parent.children[i] = wrapper
                log.append(f"mesh: sharded {node.name} across {ndev} chips")
                return
        return
    if isinstance(node, (TpuFilterExec, TpuProjectExec)) or \
            (isinstance(node, TpuHashAggregateExec)
             and node.mode == "partial"):
        for c in list(node.children):
            _shard_scans(c, node, conf, ndev, log)
