"""Sharded scans and zero-copy shard plumbing for mesh execution.

Two halves:

  * `MeshShardedScanExec` — wraps a planned scan and partitions its input
    across mesh positions: parquet scans split at ROW-GROUP granularity
    (every chip decodes its own row-group range through the existing
    io/parquet_device fast path), multi-file scans split at FILE
    granularity, in-memory scans at ROW ranges. Each shard's batch is
    committed to its own device, so the downstream exchange can assemble
    its global input with `jax.make_array_from_single_device_arrays` —
    zero copies, no device-0 concat bounce — and downstream per-shard
    kernels (zipped join, partial aggregate) dispatch on the shard's own
    chip.

  * shard-view helpers — `aligned_device_shards` (is this batch stream an
    ndev-aligned set of per-device shards?), `assemble_exchange_input`
    (per-shard leaves -> globally-sharded arrays + partition ids computed
    PER SHARD on each device), and `shard_view` (device-p view of an
    exchanged global array via `addressable_shards`, replacing the
    compiled gather-to-replicated slice the dryrun path used — the
    "partitions stay device-resident between stages" contract).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch, empty_batch
from ..exec.base import UnaryTpuExec
from ..utils import spans

__all__ = ["MeshShardedScanExec", "aligned_device_shards",
           "assemble_exchange_input", "shard_view"]


# ---------------------------------------------------------------------------
# sharded scan
# ---------------------------------------------------------------------------

class MeshShardedScanExec(UnaryTpuExec):
    """Partition a scan's input across mesh positions, one output batch
    per chip (positionally aligned, empties included), each committed to
    its own device. Rides the EXISTING io/ decoders per shard — a shard
    is just the inner scan restricted to its row-group/file/row range.

    With `spark.rapids.tpu.mesh.scan.parallel` the shards decode on
    worker threads that ADOPT the query's one admission hold
    (mesh/admission.py — never per-chip token storms) and park finished
    shards as budget-visible chip-tagged spillables until the consumer
    drains them in mesh order."""

    def __init__(self, inner, conf=None):
        super().__init__([inner], conf or inner.conf)

    @property
    def name(self) -> str:
        return f"MeshShardedScanExec({self.child.name})"

    def _arg_string(self) -> str:
        return ""

    # -- shard planning ----------------------------------------------------
    def _mesh(self):
        from ..parallel.mesh import mesh_from_conf
        mesh = mesh_from_conf(self.conf)
        if mesh is None:
            raise RuntimeError("MeshShardedScanExec without an active mesh "
                               "(plan pass applied outside mesh mode)")
        return mesh

    def _shard_plans(self, ndev: int) -> List[dict]:
        """One work descriptor per mesh position. Shapes:
        {"kind": "files", "paths": [...], "rgs": {path: frozenset}|None}
        or {"kind": "rows", "off": int, "len": int}."""
        from ..exec.basic import TpuScanExec
        from ..io.scanbase import TpuFileScanExec
        inner = self.child
        if isinstance(inner, TpuFileScanExec):
            return self._file_shard_plans(inner, ndev)
        if isinstance(inner, TpuScanExec):
            n = inner.table.num_rows
            per = -(-max(n, 0) // ndev) if n else 0
            return [{"kind": "rows", "off": min(p * per, n),
                     "len": max(min((p + 1) * per, n) - min(p * per, n), 0)}
                    for p in range(ndev)]
        raise TypeError(f"cannot shard {type(inner).__name__}")

    def _file_shard_plans(self, inner, ndev: int) -> List[dict]:
        scan = inner.cpu_scan
        paths = list(scan.paths)
        units = self._rowgroup_units(inner, paths)
        if units is None:
            # FILE granularity: contiguous path ranges per shard (shards
            # past the file count scan nothing)
            per = -(-len(paths) // ndev) if paths else 0
            return [{"kind": "files",
                     "paths": paths[p * per:(p + 1) * per], "rgs": None}
                    for p in range(ndev)]
        per = -(-len(units) // ndev) if units else 0
        out = []
        for p in range(ndev):
            mine = units[p * per:(p + 1) * per]
            rgs: Dict[str, set] = {}
            for path, rg in mine:
                rgs.setdefault(path, set()).add(rg)
            # sorted tuples (not sets): the values render into the rescache
            # scan-fragment fingerprint, so two shards of the same file can
            # never alias one cache entry
            out.append({"kind": "files", "paths": [pa for pa in paths
                                                   if pa in rgs],
                        "rgs": {k: tuple(sorted(v))
                                for k, v in rgs.items()}})
        return out

    def _rowgroup_units(self, inner, paths) -> Optional[List[Tuple[str, int]]]:
        """(path, row_group) units when EVERY file will take the device
        parquet decode (whose row-group loop honors `shard_rgs`); None
        falls shard planning back to file granularity — a whole-file host
        fallback would otherwise re-read the full file in every shard
        that owns one of its row groups (a wrong split, not a slow one).
        `shard_rgs` also renders into the clone's rescache fingerprint
        (scanbase class-attr contract), keeping per-shard cache entries
        distinct."""
        scan = inner.cpu_scan
        if scan.format_name != "parquet" or scan.options.get("filters") \
                or not self.conf.get(
                    "spark.rapids.sql.format.parquet.deviceDecode.enabled"):
            return None
        try:
            from ..io.parquet_device import columns_supported
            units: List[Tuple[str, int]] = []
            for path in paths:
                pf, bad = columns_supported(path, scan.output)
                try:
                    # read the footer from the sweep's own handle — a
                    # second open per file would leak a descriptor here
                    # at plan time (the no-fd-outlives-its-file
                    # discipline scanbase's check() documents)
                    nrg = pf.metadata.num_row_groups
                finally:
                    close = getattr(pf, "close", None)
                    if close is not None:
                        close()
                if len(bad) >= len(scan.output.names):
                    return None
                units.extend((path, rg) for rg in range(nrg))
            return units or None
        except Exception:
            return None

    # -- shard production --------------------------------------------------
    def _shard_clone(self, plan: dict):
        """Inner scan restricted to one shard's range (shared conf,
        metrics, pushed spec, dynamic filters — only the input range
        differs)."""
        from ..exec.basic import TpuScanExec
        inner = self.child
        if plan["kind"] == "rows":
            return None  # handled inline in _produce_shard
        clone = copy.copy(inner)
        cs = copy.copy(inner.cpu_scan)
        cs.paths = list(plan["paths"])
        for attr in ("_footer_meta_cache", "_footer_rows", "_col_stats"):
            if hasattr(cs, attr):
                delattr(cs, attr)
        clone.cpu_scan = cs
        clone.shard_rgs = plan["rgs"]
        return clone

    def _produce_shard(self, p: int, plan: dict, device) -> ColumnarBatch:
        from ..columnar.batch import batch_from_arrow
        from ..exec.coalesce import concat_batches
        if plan["kind"] == "rows":
            if plan["len"] <= 0:
                b = empty_batch(self.output, 1)
            else:
                chunk = self.child.table.slice(plan["off"], plan["len"])
                b = batch_from_arrow(chunk)
                # the row-range path bypasses the inner exec's iterator;
                # keep its metrics truthful (stats history reads them)
                self.child.num_output_rows.add(chunk.num_rows)
                self.child.num_output_batches.add(1)
        else:
            clone = self._shard_clone(plan)
            batches = list(clone.execute()) if clone.cpu_scan.paths else []
            if not batches:
                b = empty_batch(self.output, 1)
            elif len(batches) == 1:
                b = batches[0]
            else:
                b = concat_batches(batches)
        # commit the shard to ITS chip: downstream kernels dispatch there,
        # and the exchange assembles the global array zero-copy
        return jax.device_put(b, device)

    def do_execute(self):
        mesh = self._mesh()
        ndev = mesh.size
        devs = list(mesh.devices.flat)
        plans = self._shard_plans(ndev)
        from ..utils.metrics import TaskMetrics
        TaskMetrics.get().mesh_shards += ndev
        with spans.span("mesh:scan", kind=spans.KIND_IO, shards=ndev):
            pass
        if self.conf.get("spark.rapids.tpu.mesh.scan.parallel"):
            yield from self._parallel_shards(plans, devs)
            return
        for p in range(ndev):
            b = self._produce_shard(p, plans[p], devs[p])
            self.num_output_rows.add(b.row_count())
            yield self._count_output(b)

    def _parallel_shards(self, plans, devs):
        """Concurrent per-shard decode under the ONE-admission-door
        discipline: workers adopt the query's hold, park results as
        chip-tagged spillables, and the consumer drains in mesh order."""
        import threading
        from ..memory.catalog import SpillPriority
        from ..memory.spillable import SpillableColumnarBatch
        from .admission import QueryScope, shard_worker_scope
        scope = QueryScope()
        ndev = len(devs)
        results: list = [None] * ndev
        errors: list = [None] * ndev

        def work(p):
            try:
                with shard_worker_scope(scope):
                    b = self._produce_shard(p, plans[p], devs[p])
                    results[p] = SpillableColumnarBatch(
                        b, priority=SpillPriority.BUFFERED, chip=devs[p].id)
            except BaseException as e:  # noqa: BLE001 — crosses the join
                errors[p] = e

        threads = [threading.Thread(target=work, args=(p,),
                                    name=f"srtpu-mesh-shard-{p}", daemon=True)
                   for p in range(ndev)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        try:
            for e in errors:
                if e is not None:
                    raise e
            for p in range(ndev):
                sp = results[p]
                results[p] = None
                try:
                    b = sp.get_batch(acquire_semaphore=False)
                finally:
                    sp.close()
                self.num_output_rows.add(b.row_count())
                yield self._count_output(b)
        finally:
            for sp in results:
                if sp is not None:
                    sp.close()


# ---------------------------------------------------------------------------
# shard-view helpers (used by exec/exchange.py's mesh path)
# ---------------------------------------------------------------------------

def aligned_device_shards(batches: Sequence[ColumnarBatch],
                          mesh) -> Optional[List[ColumnarBatch]]:
    """The stream IS an ndev-aligned set of per-device shards: exactly one
    batch per mesh position, committed to that position's device, flat
    columns only (nested children and long-string overflow fall back to
    the concat path — their layouts are not uniformly shardable)."""
    devs = list(mesh.devices.flat)
    if len(batches) != len(devs):
        return None
    for p, b in enumerate(batches):
        if not b.columns:
            return None
        for c in b.columns:
            if c.children or c.overflow is not None:
                return None
            d = c.data
            if not getattr(d, "committed", False):
                return None
            if d.devices() != {devs[p]}:
                return None
    return list(batches)


def _pad_width(a, tgt: Tuple[int, ...]):
    if a.shape[1:] == tgt:
        return a
    pads = [(0, 0)] + [(0, t - s) for s, t in zip(a.shape[1:], tgt)]
    return jnp.pad(a, pads)


def assemble_exchange_input(shards: List[ColumnarBatch], mesh, part):
    """Per-device shard batches -> (global leaves, global pid,
    has_lengths, cap) with NO host or device-0 concat: every shard is
    padded to the common capacity ON ITS OWN DEVICE, partition ids are
    computed per shard on that device (hash ids are row-local, so the
    shard-wise computation equals the global one), and the global
    [ndev*cap] arrays are stitched with
    `jax.make_array_from_single_device_arrays` — zero copies.

    Returns None when the per-device shards are not addressable from this
    process (multi-host meshes fall back to the concat path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..columnar.batch import Column
    from ..columnar.padding import row_bucket
    from ..parallel.mesh import SHUFFLE_AXIS
    ndev = mesh.size
    me = jax.process_index()
    if any(d.process_index != me for d in mesh.devices.flat):
        return None  # multi-host mesh: shards not all addressable here
    rows = [int(b.row_count()) for b in shards]
    cap = row_bucket(max(max(rows), 1))
    ncols = len(shards[0].columns)
    widths = [tuple(max(b.columns[ci].data.shape[1:][d]
                        for b in shards)
                    for d in range(shards[0].columns[ci].data.ndim - 1))
              for ci in range(ncols)]
    has_lengths = [shards[0].columns[ci].lengths is not None
                   for ci in range(ncols)]
    per_shard_leaves: List[List] = []
    pid_shards: List = []
    for b in shards:
        g = b.repadded(cap)
        cols = []
        for ci, c in enumerate(g.columns):
            data = _pad_width(c.data, widths[ci])
            if data is not c.data:
                c = Column(c.dtype, data, c.validity, c.lengths)
            cols.append(c)
        g = ColumnarBatch(b.schema, tuple(cols), g.num_rows)
        pid_shards.append(part.ids_for_batch(jnp, g).astype(jnp.int32))
        leaves = []
        for ci, c in enumerate(g.columns):
            leaves.append(c.data)
            leaves.append(c.validity)
            if has_lengths[ci]:
                leaves.append(c.lengths)
        per_shard_leaves.append(leaves)
    sh = NamedSharding(mesh, P(SHUFFLE_AXIS))

    def stitch(parts):
        shape = (ndev * cap,) + parts[0].shape[1:]
        return jax.make_array_from_single_device_arrays(shape, sh,
                                                        list(parts))

    nleaves = len(per_shard_leaves[0])
    leaves = [stitch([per_shard_leaves[p][i] for p in range(ndev)])
              for i in range(nleaves)]
    pid = stitch(pid_shards)
    return leaves, pid, has_lengths, cap


def shard_view(arr, p: int, per_rows: int):
    """Device-p rows [p*per_rows, (p+1)*per_rows) of a P(axis)-sharded
    global array, zero-copy via addressable_shards — the exchanged
    partition stays resident on its own chip instead of gathering to a
    replicated layout. None when that shard is not addressable here."""
    for s in arr.addressable_shards:
        idx = s.index[0]
        start = 0 if idx.start is None else idx.start
        if start == p * per_rows:
            return s.data
    return None
