"""One admission door per mesh.

A mesh-active query fans out over N chips, and with
`spark.rapids.tpu.mesh.scan.parallel` over N shard decode threads — but it
is still ONE query holding ONE admission grant. Per-chip (or per-thread)
token acquisition would storm the scheduler: with concurrentGpuTasks=1 a
worker taking its own permit while the task thread holds the only one
deadlocks outright (the exact trap PR-5's prefetch producer hit), and with
more permits an 8-shard query would consume the whole pool and starve
every other tenant.

`shard_worker_scope` is therefore the single discipline every mesh worker
thread runs under: it ADOPTS the consuming task's standing — TaskMetrics
instance, semaphore hold (`adopt_task_hold`), cancel token, live-view
entry — exactly like exec/base.py's PrefetchIterator producer, and unwinds
its reentrant counts on exit without releasing the task's permit.
"""

from __future__ import annotations

import contextlib


class QueryScope:
    """Snapshot of the consuming task's execution identity, captured on
    the task thread BEFORE workers spawn."""

    __slots__ = ("tm", "ctx", "live_entry")

    def __init__(self):
        from .. import live as _live
        from ..sched import context as _qctx
        from ..utils.metrics import TaskMetrics
        self.tm = TaskMetrics.get()
        self.ctx = _qctx.current()
        self.live_entry = _live.current_entry()


@contextlib.contextmanager
def shard_worker_scope(scope: QueryScope):
    """Run a mesh shard worker thread on behalf of the query that spawned
    it: shared task counters, the task's ONE admission hold (reentrant,
    never a second permit), the task's cancel token and live entry. The
    finally unwinds only this thread's reentrant counts."""
    from .. import live as _live
    from ..memory.semaphore import TpuSemaphore
    from ..sched import context as _qctx
    from ..utils.metrics import TaskMetrics
    TaskMetrics._tls.metrics = scope.tm
    sem = TpuSemaphore.get()
    sem.adopt_task_hold()
    _qctx.adopt(scope.ctx)
    _live.adopt_entry(scope.live_entry)
    try:
        yield
    finally:
        sem.complete_task()
