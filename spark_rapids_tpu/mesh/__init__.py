"""Sharded execution over the ICI mesh — real multi-chip query execution.

This package promotes the MULTICHIP dryrun (plan -> mesh collectives on 8
devices, MULTICHIP_r05) into the default execution path for planned
queries. Theseus (arXiv 2508.05029) is the blueprint: the multi-accelerator
win is minimising data movement — shuffled partitions stay resident on
their own chip between pipeline stages instead of bouncing through host
memory, and the interconnect (ICI all-to-all), not a host TCP data plane,
moves rows between chips.

Pieces:

  * plan.py      — the sharded plan pass, hooked in `Overrides.apply` like
                   plan/scan_pushdown.py: partitions scans across mesh
                   positions, resizes safe hash-exchange boundaries to the
                   mesh, and marks the exchange->join/agg seams that keep
                   their partitions device-resident;
  * shard.py     — `MeshShardedScanExec` (row-group/file/row ranges per
                   mesh position riding the existing io/ decoders) and the
                   zero-copy shard plumbing (aligned per-device exchange
                   input assembly via make_array_from_single_device_arrays,
                   per-device output views via addressable_shards);
  * admission.py — the ONE-admission-door discipline: shard workers adopt
                   the query's existing hold (TaskMetrics / semaphore /
                   cancel token / live entry), never per-chip token storms.

Off-path contract (the established discipline): with
`spark.rapids.tpu.mesh.enabled=false` (default) nothing in this package is
imported on the engine path, plans and results are byte-identical, and
zero threads are spawned — scripts/mesh_matrix.sh gates it.
"""

from __future__ import annotations

from typing import Optional

# process-level latch: flips the first time the sharded plan pass engages.
# Cheap guards elsewhere (e.g. chip tagging in SpillableColumnarBatch) key
# off sys.modules + this bool so the mesh-off path stays one dict probe.
_ACTIVE = False

# process-wide count of plans the sharded pass rewrote (test hook, like
# exec/exchange.py MESH_EXCHANGES)
MESH_PLANS = 0


def is_active() -> bool:
    return _ACTIVE


def note_active() -> None:
    global _ACTIVE, MESH_PLANS
    _ACTIVE = True
    MESH_PLANS += 1


def mesh_enabled(conf) -> bool:
    """True when the sharded-execution subsystem applies to this conf:
    master switch on, ICI data plane selected, and a >1-device mesh shape
    configured. One conf read each — no jax, no mesh construction."""
    if not conf.get("spark.rapids.tpu.mesh.enabled"):
        return False
    if conf.get("spark.rapids.shuffle.mode") != "ICI":
        return False
    shape = (conf.get("spark.rapids.tpu.mesh.shape") or "").strip()
    if not shape:
        return False
    try:
        return int(shape.split(",")[0].split("=")[-1]) > 1
    except ValueError:
        return False


def chip_of(batch) -> Optional[int]:
    """The chip (device id) a shard batch is committed to, or None when it
    is not a single-device committed batch. The per-chip HBM ledgers
    (memory/budget.py) key on this: a shard parked on chip 3 charges chip
    3's sub-budget only."""
    try:
        cols = batch.columns
        if not cols:
            return None
        data = cols[0].data
        if not getattr(data, "committed", False):
            return None
        devs = data.devices()
        if len(devs) != 1:
            return None
        return int(next(iter(devs)).id)
    except Exception:
        return None
