"""Iceberg table read support (reference `sql-plugin/.../iceberg/` — 6k LoC
of forked reader classes; here the table format is implemented directly
against the Iceberg spec and the data files ride the existing parquet scan,
device decode included).

Layout walked (Iceberg spec v1/v2):
  <table>/metadata/vN.metadata.json   (or version-hint.text naming N)
    -> snapshots[] each with a manifest-list AVRO file
      -> manifest list entries: manifest_path (+ content kind in v2)
        -> manifest AVRO files: entries of (status, data_file record)
          -> live parquet data files

The manifest plumbing reuses io/avro.py (the from-scratch OCF reader), so no
Iceberg or Avro library is needed. Row-level deletes (v2 position/equality
delete files) are detected and rejected with a clear error — the reference
likewise tags delete-bearing scans unsupported. Time travel by snapshot id
or timestamp rides the snapshot log."""

from __future__ import annotations

import json
import os
from typing import List, Optional

from .. import types as T
from ..columnar.batch import Schema

__all__ = ["IcebergTable", "IcebergError", "IcebergDeletesUnsupported"]


class IcebergError(ValueError):
    pass


class IcebergDeletesUnsupported(IcebergError):
    pass


def _field_type(t) -> T.DataType:
    """Iceberg schema type (JSON) -> engine type."""
    if isinstance(t, str):
        prim = {
            "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG,
            "float": T.FLOAT, "double": T.DOUBLE, "date": T.DATE,
            "timestamp": T.TIMESTAMP, "timestamptz": T.TIMESTAMP,
            "string": T.STRING, "binary": T.BINARY, "uuid": T.STRING,
        }
        if t in prim:
            return prim[t]
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return T.DecimalType(int(p), int(s))
        raise IcebergError(f"unsupported iceberg type {t!r}")
    kind = t.get("type")
    if kind == "struct":
        return T.StructType([
            T.StructField(f["name"], _field_type(f["type"]))
            for f in t["fields"]])
    if kind == "list":
        return T.ArrayType(_field_type(t["element"]))
    if kind == "map":
        return T.MapType(_field_type(t["key"]), _field_type(t["value"]))
    raise IcebergError(f"unsupported iceberg type {t!r}")


def _schema_from_metadata(meta: dict) -> Schema:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        sch = next((s for s in schemas if s.get("schema-id") == sid),
                   schemas[-1])
    else:
        sch = meta["schema"]  # v1 single-schema form
    names, types = [], []
    for f in sch["fields"]:
        names.append(f["name"])
        types.append(_field_type(f["type"]))
    return Schema(tuple(names), tuple(types))


class IcebergTable:
    """A read-only view of an Iceberg table rooted at `path`."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = str(path)
        self.meta_dir = os.path.join(self.path, "metadata")
        if not os.path.isdir(self.meta_dir):
            raise IcebergError(f"not an iceberg table: {path}")
        self.metadata = self._load_metadata()
        self.schema = _schema_from_metadata(self.metadata)

    # -------------------------------------------------------------- metadata
    def _load_metadata(self) -> dict:
        hint = os.path.join(self.meta_dir, "version-hint.text")
        candidates: List[str] = []
        if os.path.exists(hint):
            with open(hint) as f:
                v = f.read().strip()
            candidates.append(os.path.join(self.meta_dir,
                                           f"v{v}.metadata.json"))
        versions = sorted(
            (f for f in os.listdir(self.meta_dir)
             if f.endswith(".metadata.json")),
            key=lambda f: _version_ordinal(f))
        candidates.extend(os.path.join(self.meta_dir, f)
                          for f in reversed(versions))
        for c in candidates:
            if os.path.exists(c):
                with open(c) as f:
                    return json.load(f)
        raise IcebergError(f"no metadata.json under {self.meta_dir}")

    @property
    def snapshots(self) -> List[dict]:
        return self.metadata.get("snapshots", [])

    def current_snapshot(self) -> Optional[dict]:
        sid = self.metadata.get("current-snapshot-id")
        if sid in (None, -1):
            return None
        return self._snapshot_by_id(sid)

    def _snapshot_by_id(self, sid: int) -> dict:
        for s in self.snapshots:
            if s.get("snapshot-id") == sid:
                return s
        raise IcebergError(f"snapshot {sid} not found")

    def snapshot_as_of(self, timestamp_ms: int) -> dict:
        """Latest snapshot with timestamp-ms <= the given time."""
        eligible = [s for s in self.snapshots
                    if s.get("timestamp-ms", 0) <= timestamp_ms]
        if not eligible:
            raise IcebergError(
                f"no snapshot at or before timestamp {timestamp_ms}")
        return max(eligible, key=lambda s: s.get("timestamp-ms", 0))

    # ------------------------------------------------------------- planning
    def _resolve_path(self, p: str) -> str:
        """Manifest/data paths may be absolute URIs from another filesystem;
        re-root anything containing the table name onto the local root."""
        if os.path.exists(p):
            return p
        if p.startswith("file://"):  # only local URIs; s3://gs:// etc. fall
            q = p[len("file://"):]   # through to the re-rooting heuristic
            if os.path.exists(q):
                return q
        # re-root by the table directory name
        base = os.path.basename(self.path.rstrip("/"))
        if f"/{base}/" in p:
            rel = p.split(f"/{base}/", 1)[1]
            q = os.path.join(self.path, rel)
            if os.path.exists(q):
                return q
        raise IcebergError(f"cannot resolve file {p!r}")

    def data_files(self, snapshot_id: Optional[int] = None,
                   as_of_timestamp_ms: Optional[int] = None) -> List[str]:
        """Live parquet data files of the chosen snapshot. Raises
        IcebergDeletesUnsupported when the snapshot carries row-level delete
        files (the scan would return resurrected rows otherwise)."""
        from ..io.avro import read_avro_table
        if snapshot_id is not None:
            snap = self._snapshot_by_id(snapshot_id)
        elif as_of_timestamp_ms is not None:
            snap = self.snapshot_as_of(as_of_timestamp_ms)
        else:
            snap = self.current_snapshot()
        if snap is None:
            return []
        mlist_path = self._resolve_path(snap["manifest-list"])
        mlist = read_avro_table(mlist_path).to_pylist()
        files: List[str] = []
        for m in mlist:
            if m.get("content", 0) == 1:  # v2 delete manifest
                raise IcebergDeletesUnsupported(
                    "iceberg row-level deletes are not supported "
                    "(delete manifest present)")
            mpath = self._resolve_path(m["manifest_path"])
            for entry in read_avro_table(mpath).to_pylist():
                if entry.get("status", 0) == 2:  # DELETED entry
                    continue
                df = entry["data_file"]
                if df.get("content", 0) != 0:  # v2 delete data file
                    raise IcebergDeletesUnsupported(
                        "iceberg row-level deletes are not supported")
                fmt = str(df.get("file_format", "PARQUET")).upper()
                if fmt != "PARQUET":
                    raise IcebergError(
                        f"iceberg data file format {fmt} not supported")
                files.append(self._resolve_path(df["file_path"]))
        return files

    # -------------------------------------------------------------- reading
    def _check_schema_evolution(self, files: List[str]) -> None:
        """Data files are resolved by parquet column NAME, not Iceberg field
        id — correct only while file schemas match the table schema. Detect
        renamed/added columns (old files carrying old names) and reject
        loudly, the same unsupported-tagging discipline as deletes."""
        import pyarrow.parquet as pq
        want = set(self.schema.names)
        for f in files:
            got = set(pq.read_schema(f).names)
            if got != want:
                raise IcebergError(
                    "schema-evolved iceberg table: data file "
                    f"{os.path.basename(f)} has columns {sorted(got)} but "
                    f"the table schema has {sorted(want)} (field-id "
                    "resolution is not supported)")

    def scan_plan(self, columns=None, snapshot_id=None,
                  as_of_timestamp_ms=None):
        from ..io.parquet import parquet_scan_plan
        files = self.data_files(snapshot_id, as_of_timestamp_ms)
        self._check_schema_evolution(files)
        if not files:
            from ..plan.nodes import CpuScanExec
            import pyarrow as pa
            empty = pa.table(
                [pa.array([], type=T.to_arrow(dt)) for dt in self.schema.types],
                names=list(self.schema.names))
            if columns:
                empty = empty.select(columns)
            return CpuScanExec(empty, "iceberg-empty")
        return parquet_scan_plan(files, self.session.conf, columns=columns)

    def to_df(self, columns=None, snapshot_id=None, as_of_timestamp_ms=None):
        from ..frontend import DataFrame
        return DataFrame(self.session,
                         self.scan_plan(columns, snapshot_id,
                                        as_of_timestamp_ms))


def _version_ordinal(fname: str) -> int:
    """v12.metadata.json -> 12; 00003-uuid.metadata.json -> 3."""
    stem = fname[:-len(".metadata.json")]
    if stem.startswith("v") and stem[1:].isdigit():
        return int(stem[1:])
    head = stem.split("-", 1)[0]
    return int(head) if head.isdigit() else -1
