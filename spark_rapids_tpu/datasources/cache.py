"""Cached-relation serializer (`df.cache()`) — the
`ParquetCachedBatchSerializer.scala:221` analog: cached data is stored as
COMPRESSED PARQUET bytes, not live device arrays, so a big cache costs host
RAM at parquet compression ratios instead of pinning HBM, and re-reading it
rides the same decode machinery as a parquet scan.

TPU-native twist: blobs are written PLAIN-encoded (no dictionary pages), the
exact encoding `io/parquet_device.py` decodes ON DEVICE — so a cache hit is
host-bytes -> TPU decode, mirroring the reference where both encode and
decode of cached batches run on the GPU. Anything the device decoder cannot
handle (strings, nested) falls back to pyarrow per blob, like the scan path.
"""

from __future__ import annotations

import io
import threading
import weakref
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..columnar.batch import Schema
from ..config import register
from ..plan.nodes import PhysicalPlan

# every CpuCachedExec that ever MATERIALIZED a relation, weakly — the
# telemetry gauge tpu_cached_relation_bytes sums live blob bytes over
# these, so explicit df.cache() memory shows on the scrape surface and
# drops to zero on unpersist() (host RAM held by the serializer was
# previously invisible to operators)
_LIVE_CACHED: "weakref.WeakSet" = weakref.WeakSet()


def live_cached_execs():
    return list(_LIVE_CACHED)

register("spark.rapids.sql.cache.compression", "string", "zstd",
         "Parquet compression codec for cached batches "
         "(ParquetCachedBatchSerializer analog).",
         check_values=("none", "snappy", "zstd", "gzip"))


class CachedRelation:
    """Immutable parquet-bytes snapshot of a query result."""

    def __init__(self, blobs: List[bytes], schema: Schema, num_rows: int):
        self.blobs = blobs
        self.schema = schema
        self.num_rows = num_rows

    @property
    def size_bytes(self) -> int:
        return sum(len(b) for b in self.blobs)


def encode_table(table: pa.Table, codec: str) -> bytes:
    buf = io.BytesIO()
    # one row group per blob: the device decode path emits one batch per
    # group, and cached batches are already batch-sized
    pq.write_table(table, buf, use_dictionary=False,
                   row_group_size=max(table.num_rows, 1),
                   compression=None if codec == "none" else codec)
    return buf.getvalue()


def decode_blob(blob: bytes) -> pa.Table:
    return pq.read_table(io.BytesIO(blob))


class CpuCachedExec(PhysicalPlan):
    """Plan node holding the cache state. The SAME node object persists
    across collects (it lives in df.plan), so whichever engine materializes
    first feeds every later execution on either engine — Spark's
    InMemoryRelation sharing, without the storage-level zoo."""

    def __init__(self, child: PhysicalPlan, codec: str = "zstd"):
        super().__init__([child])
        self.codec = codec
        self.relation: Optional[CachedRelation] = None
        self.lock = threading.Lock()

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def unpersist(self) -> None:
        with self.lock:
            # dropping the relation releases the parquet blob bytes (the
            # only strong reference); the cached-relation telemetry gauge
            # reads 0 for this node from here on
            self.relation = None

    def store_tables(self, tables: List[pa.Table]) -> CachedRelation:
        with self.lock:
            if self.relation is not None:
                return self.relation
            blobs = [encode_table(t, self.codec) for t in tables if t.num_rows]
            if not blobs and tables:
                blobs = [encode_table(tables[0], self.codec)]
            self.relation = CachedRelation(
                blobs, self.output, sum(t.num_rows for t in tables))
            _LIVE_CACHED.add(self)  # blob bytes become gauge-visible
            return self.relation

    def execute_cpu(self):
        from ..cpu.hostbatch import host_batch_from_arrow, host_batch_to_arrow
        rel = self.relation  # snapshot: concurrent unpersist() must not crash
        if rel is None:
            tables = [host_batch_to_arrow(b)
                      for b in self.children[0].execute_cpu()]
            rel = self.store_tables(tables)
        for blob in rel.blobs:
            yield host_batch_from_arrow(decode_blob(blob))

    def _arg_string(self):
        state = "materialized" if self.relation is not None else "lazy"
        return f"[{state}, codec={self.codec}]"


from ..exec.base import TpuExec as _TpuExec  # noqa: E402


class TpuInMemoryTableScanExec(_TpuExec):
    """Device exec over a cached relation (GpuInMemoryTableScanExec analog).
    First execution materializes THROUGH the device child plan (encode from
    device results); later executions decode the parquet blobs straight onto
    the device where the encodings allow."""

    def __init__(self, plan: CpuCachedExec, child: _TpuExec, conf):
        super().__init__([child], conf)
        self.cpu_node = plan

    @property
    def output(self) -> Schema:
        return self.cpu_node.output

    def do_execute(self):
        from ..columnar.batch import batch_to_arrow
        node = self.cpu_node
        rel = node.relation  # snapshot: concurrent unpersist() must not crash
        if rel is None:
            tables = []
            for b in self.children[0].execute():
                t = batch_to_arrow(b)
                tables.append(t)
                self.num_output_rows.add(t.num_rows)
                yield self._count_output(b)
            node.store_tables(tables)
            return
        for blob in rel.blobs:
            b, nrows = self._decode_device(blob)
            self.num_output_rows.add(nrows)
            yield self._count_output(b)

    def _decode_device(self, blob: bytes):
        from ..columnar.batch import batch_from_arrow
        from ..io.parquet_device import (DeviceDecodeUnsupported,
                                         decode_row_group, file_supported)
        from ..io.scanbase import normalize_timestamps
        from struct import error as struct_error
        if self.conf.get("spark.rapids.sql.format.parquet.deviceDecode."
                         "enabled"):
            try:
                pf = file_supported(io.BytesIO(blob), self.output)
                # encode_table writes exactly one row group per blob; check
                # BEFORE decoding so an unexpected multi-group blob costs a
                # host decode, never device work thrown away
                if pf.metadata.num_row_groups == 1:
                    return decode_row_group(pf, io.BytesIO(blob), 0,
                                            self.output)
            except (DeviceDecodeUnsupported, OSError, struct_error):
                pass
        t = normalize_timestamps(decode_blob(blob))
        return batch_from_arrow(t), t.num_rows

    def _arg_string(self):
        return self.cpu_node._arg_string()
