from .table import DeltaTable, src  # noqa: F401
