"""Z-order clustering (reference `zorder/ZOrderRules.scala` +
`GpuHilbertLongIndex.scala` / deltalake's OPTIMIZE ZORDER BY).

The reference replaces delta's zorder expressions with GPU versions:
each clustering column normalizes to an int rank (range partitioning),
the ranks' bits interleave into one morton key, and the table sorts by
it. Here the same three steps run on device: rank via double-argsort
(ties keep file order — stable), bit interleave as a static unrolled
shift/or loop (bits * ncols <= 63), and the engine's device sort orders
the rewrite. Hilbert indexing (the reference's alternative curve) is not
implemented yet — morton/z-order is what OPTIMIZE ZORDER defaults to."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ... import types as T
from ...expr.base import EvalContext, Expression, Vec

__all__ = ["InterleaveBits", "zorder_indices"]


class InterleaveBits(Expression):
    """interleave_bits(c1, ..., ck): normalize each child to an unsigned
    `bits`-wide rank by its batch-local sort position, then weave bit b of
    child i into output bit b*k + i — the morton key OPTIMIZE ZORDER
    sorts by (reference ZOrderRules' InterleaveBits replacement)."""

    def __init__(self, children: Sequence[Expression], bits: int = 16):
        super().__init__(list(children))
        k = max(len(self.children), 1)
        self.bits = min(int(bits), 63 // k)

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def _compute(self, ctx: EvalContext, *cols: Vec) -> Vec:
        xp = ctx.xp
        n = cols[0].data.shape[0] if cols else 1
        mask = ctx.row_mask
        ranks = [self._rank(xp, v, mask, n) for v in cols]
        out = xp.zeros(n, np.int64)
        k = len(cols)
        for b in range(self.bits):  # static unroll: bits*k or/shift pairs
            for ci, r in enumerate(ranks):
                bit = (r >> np.int64(b)) & np.int64(1)
                out = out | (bit << np.int64(b * k + ci))
        return Vec(T.LONG, out, xp.ones(n, dtype=bool))

    def _rank(self, xp, v: Vec, mask, n: int):
        """Batch-local dense position scaled to [0, 2^bits): the engine's
        analog of the reference's range-partition normalization (exact
        quantiles of THIS data, nulls first like Spark sort defaults)."""
        from ...ops.rowops import sort_keys_for
        keys = sort_keys_for(xp, v, True, True)
        live = mask if mask is not None else xp.ones(n, dtype=bool)
        # order live rows first by key; dead rows park at the end
        from ...ops.rowops import lexsort_indices
        composite = [(~live).astype(np.int8)] + list(keys)
        order = lexsort_indices(xp, [[k] for k in composite], n)
        pos = xp.zeros(n, np.int64)
        if xp is np:
            pos[order] = np.arange(n, dtype=np.int64)
        else:
            pos = pos.at[order].set(xp.arange(n, dtype=np.int64))
        n_live = live.sum().astype(np.int64) if mask is not None \
            else np.int64(n)
        denom = xp.maximum(n_live, np.int64(1))
        scaled = (pos * ((1 << self.bits) - 1)) // denom
        return xp.clip(scaled, 0, (1 << self.bits) - 1)


def zorder_indices(session, table, columns: Sequence[str],
                   bits: int = 16) -> np.ndarray:
    """Row ordering for OPTIMIZE ZORDER BY: morton keys computed on the
    device engine, returned as a host permutation."""
    import jax.numpy as jnp
    from ...columnar.batch import batch_from_arrow
    from ...expr.base import BoundReference
    batch = batch_from_arrow(table)
    names = list(table.schema.names)
    refs = []
    for c in columns:
        i = names.index(c)
        refs.append(BoundReference(i, T.from_arrow(table.schema.types[i])))
    expr = InterleaveBits(refs, bits=bits)
    from ...exec.base import batch_vecs
    ctx = EvalContext(jnp, row_mask=batch.row_mask())
    z = expr.eval(ctx, batch_vecs(batch))
    zh = np.asarray(z.data)[:table.num_rows]
    return np.argsort(zh, kind="stable")
