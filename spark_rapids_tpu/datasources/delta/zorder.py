"""Z-order clustering (reference `zorder/ZOrderRules.scala` +
`GpuHilbertLongIndex.scala` / deltalake's OPTIMIZE ZORDER BY).

The reference replaces delta's zorder expressions with GPU versions:
each clustering column normalizes to an int rank (range partitioning),
the ranks' bits interleave into one morton key, and the table sorts by
it. Here the same three steps run on device: rank via double-argsort
(ties keep file order — stable), bit interleave as a static unrolled
shift/or loop (bits * ncols <= 63), and the engine's device sort orders
the rewrite. HilbertLongIndex provides the reference's alternative curve
(Skilling transform, validated against a scalar oracle + the unit-step
property); morton/z-order stays the OPTIMIZE default."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ... import types as T
from ...expr.base import EvalContext, Expression, Vec

__all__ = ["CURVES", "InterleaveBits", "HilbertLongIndex",
           "zorder_indices"]


class InterleaveBits(Expression):
    """interleave_bits(c1, ..., ck): normalize each child to an unsigned
    `bits`-wide rank by its batch-local sort position, then weave bit b of
    child i into output bit b*k + i — the morton key OPTIMIZE ZORDER
    sorts by (reference ZOrderRules' InterleaveBits replacement)."""

    def __init__(self, children: Sequence[Expression], bits: int = 16):
        super().__init__(list(children))
        k = max(len(self.children), 1)
        self.bits = max(min(int(bits), 63 // k), 1)

    def __repr__(self):
        # bits is unrolled into the traced program: repr-derived cache
        # keys must not alias different widths over the same children
        return f"InterleaveBits({', '.join(map(repr, self.children))}; " \
               f"bits={self.bits})"

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def _compute(self, ctx: EvalContext, *cols: Vec) -> Vec:
        xp = ctx.xp
        n = cols[0].data.shape[0] if cols else 1
        mask = ctx.row_mask
        ranks = [self._rank(xp, v, mask, n) for v in cols]
        out = xp.zeros(n, np.int64)
        k = len(cols)
        for b in range(self.bits):  # static unroll: bits*k or/shift pairs
            for ci, r in enumerate(ranks):
                bit = (r >> np.int64(b)) & np.int64(1)
                out = out | (bit << np.int64(b * k + ci))
        return Vec(T.LONG, out, xp.ones(n, dtype=bool))

    def _rank(self, xp, v: Vec, mask, n: int):
        """Batch-local dense position scaled to [0, 2^bits): the engine's
        analog of the reference's range-partition normalization (exact
        quantiles of THIS data, nulls first like Spark sort defaults)."""
        from ...ops.rowops import sort_keys_for
        keys = sort_keys_for(xp, v, True, True)
        live = mask if mask is not None else xp.ones(n, dtype=bool)
        # order live rows first by key; dead rows park at the end
        from ...ops.rowops import lexsort_indices
        composite = [(~live).astype(np.int8)] + list(keys)
        order = lexsort_indices(xp, [[k] for k in composite], n)
        pos = xp.zeros(n, np.int64)
        if xp is np:
            pos[order] = np.arange(n, dtype=np.int64)
        else:
            pos = pos.at[order].set(xp.arange(n, dtype=np.int64))
        n_live = live.sum().astype(np.int64) if mask is not None \
            else np.int64(n)
        denom = xp.maximum(n_live, np.int64(1))
        scaled = (pos * ((1 << self.bits) - 1)) // denom
        return xp.clip(scaled, 0, (1 << self.bits) - 1)


class HilbertLongIndex(InterleaveBits):
    """hilbert_index(c1, ..., ck): the reference's alternative clustering
    curve (`GpuHilbertLongIndex.scala:33`). Ranks normalize exactly like
    InterleaveBits; the coordinates then map through Skilling's transpose
    transform (vectorized — every branch is a masked select, loops are
    static over bits) before interleaving, giving the locality-preserving
    Hilbert order whose successive cells are always unit steps."""

    def _compute(self, ctx: EvalContext, *cols: Vec) -> Vec:
        xp = ctx.xp
        n = cols[0].data.shape[0] if cols else 1
        mask = ctx.row_mask
        X = [self._rank(xp, v, mask, n) for v in cols]
        k = len(X)
        b = self.bits
        M = np.int64(1 << (b - 1))
        # Skilling: axes -> transpose (inverse undo)
        Q = int(M)
        while Q > 1:
            P = np.int64(Q - 1)
            for i in range(k):
                cond = (X[i] & np.int64(Q)) != 0
                if i == 0:  # swap with self is a no-op: invert or keep
                    X[0] = xp.where(cond, X[0] ^ P, X[0])
                    continue
                t = (X[0] ^ X[i]) & P  # from the ORIGINAL pair
                X0 = X[0]
                X[0] = xp.where(cond, X0 ^ P, X0 ^ t)
                X[i] = xp.where(cond, X[i], X[i] ^ t)
            Q >>= 1
        # Gray encode
        for i in range(1, k):
            X[i] = X[i] ^ X[i - 1]
        t = xp.zeros(n, np.int64)
        Q = int(M)
        while Q > 1:
            t = xp.where((X[k - 1] & np.int64(Q)) != 0,
                         t ^ np.int64(Q - 1), t)
            Q >>= 1
        for i in range(k):
            X[i] = X[i] ^ t
        # transpose -> index: bit b of axis i lands at b*k + (k-1-i),
        # axis 0 most significant within each bit plane
        out = xp.zeros(n, np.int64)
        for bit in range(b):
            for i in range(k):
                v = (X[i] >> np.int64(bit)) & np.int64(1)
                out = out | (v << np.int64(bit * k + (k - 1 - i)))
        return Vec(T.LONG, out, xp.ones(n, dtype=bool))


# the single source of valid clustering curves (table.py validates
# against these keys)
CURVES = {"zorder": InterleaveBits, "hilbert": HilbertLongIndex}


def zorder_indices(session, table, columns: Sequence[str],
                   bits: int = 16, curve: str = "zorder") -> np.ndarray:
    """Row ordering for OPTIMIZE ZORDER BY: morton ("zorder") or hilbert
    curve keys computed on the device engine, returned as a host
    permutation."""
    import jax.numpy as jnp
    from ...columnar.batch import batch_from_arrow
    from ...expr.base import BoundReference
    batch = batch_from_arrow(table)
    names = list(table.schema.names)
    refs = []
    for c in columns:
        i = names.index(c)
        refs.append(BoundReference(i, T.from_arrow(table.schema.types[i])))
    expr = CURVES[curve](refs, bits=bits)
    from ...exec.base import batch_vecs
    ctx = EvalContext(jnp, row_mask=batch.row_mask())
    z = expr.eval(ctx, batch_vecs(batch))
    zh = np.asarray(z.data)[:table.num_rows]
    return np.argsort(zh, kind="stable")
