"""Delta-style transactional table with MERGE INTO / UPDATE / DELETE run
through the TPU engine (reference `delta-lake/delta-21x/.../
GpuMergeIntoCommand.scala:1`, `GpuUpdateCommand.scala`, `GpuDeleteCommand.scala`,
`GpuOptimisticTransaction`; BASELINE workload #4).

Storage model mirrors the Delta protocol at small scale: a directory of
parquet part files plus `_delta_log/NNNNNNNNNN.json` commits holding
`add`/`remove` actions; a reader replays the log to the requested version to
find the active file set. Commits are optimistic: the writer stakes the next
version file with O_EXCL, so two concurrent committers cannot both win.

The DML commands compile to the engine's own plan machinery — the matched/
not-matched analysis is the join machinery (left join for matched-row
transforms, anti join for inserts), so the heavy lifting runs on device via
the normal Overrides path, exactly the reference's design (its MERGE builds a
joinedDF and writes the result through the GPU writer)."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from ...expr.base import AttributeReference, Expression
from ...errors import RapidsTpuError

_SRC_PREFIX = "__src__"


def src(name: str) -> AttributeReference:
    """Reference a SOURCE column inside merge expressions (target columns are
    plain col(name); the source side is prefixed to avoid name collisions)."""
    return AttributeReference(_SRC_PREFIX + name)


class DeltaConcurrentModification(RapidsTpuError):
    pass


class DeltaMultipleMatches(RapidsTpuError):
    pass


class DeltaTable:
    """A versioned table rooted at `path`."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = str(path)
        self.log_dir = os.path.join(self.path, "_delta_log")
        if not os.path.isdir(self.log_dir):
            raise FileNotFoundError(f"not a delta table: {path}")

    # ------------------------------------------------------------- creation
    @staticmethod
    def create(session, path: str, table: pa.Table) -> "DeltaTable":
        path = str(path)
        os.makedirs(os.path.join(path, "_delta_log"), exist_ok=False)
        fname = f"part-{uuid.uuid4().hex}.parquet"
        pq.write_table(table, os.path.join(path, fname))
        _write_commit(os.path.join(path, "_delta_log"), 0, [
            {"metaData": {"schemaString": table.schema.to_string(),
                          "createdTime": int(time.time() * 1000)}},
            {"add": {"path": fname, "size": os.path.getsize(
                os.path.join(path, fname)), "dataChange": True}},
        ])
        return DeltaTable(session, path)

    # ------------------------------------------------------------- log replay
    @property
    def version(self) -> int:
        return max(self._versions())

    def _versions(self) -> List[int]:
        out = [int(f.split(".")[0]) for f in os.listdir(self.log_dir)
               if f.endswith(".json")]
        if not out:
            raise FileNotFoundError("empty delta log")
        return sorted(out)

    def active_files(self, version: Optional[int] = None) -> List[str]:
        """Replay up to `version` (inclusive), seeding from the newest
        parquet checkpoint at or below it when one exists — so long table
        histories replay O(commits since checkpoint) JSON files, matching
        the Delta protocol's `_last_checkpoint` fast path (reference Delta
        modules consume checkpoints natively; r3 verdict Missing #9). A
        version that was never committed raises (VersionNotFoundException
        analog) rather than silently clamping."""
        versions = self._versions()
        if version is not None and version not in versions:
            raise ValueError(
                f"version {version} does not exist (available: "
                f"{versions[0]}..{versions[-1]})")
        live: Dict[str, bool] = {}
        start_after = -1
        cp = self._checkpoint_at_or_below(
            versions[-1] if version is None else version)
        if cp is not None:
            cp_version, cp_adds = cp
            live = {p: True for p in cp_adds}
            start_after = cp_version
        for v in versions:
            if v <= start_after:
                continue
            if version is not None and v > version:
                break
            with open(os.path.join(self.log_dir, _commit_name(v))) as f:
                for line in f:
                    act = json.loads(line)
                    if "add" in act:
                        live[act["add"]["path"]] = True
                    elif "remove" in act:
                        live.pop(act["remove"]["path"], None)
        return [os.path.join(self.path, p) for p in live]

    # ------------------------------------------------------- checkpoints
    def _checkpoint_at_or_below(self, version: int):
        """(checkpoint_version, [add paths]) from the newest usable
        parquet checkpoint <= version, via `_last_checkpoint` first (the
        protocol's pointer file), else a directory scan; None when no
        checkpoint applies. A corrupt pointer degrades to the scan, a
        corrupt checkpoint file to full JSON replay — never an error."""
        candidates: List[int] = []
        lc = os.path.join(self.log_dir, "_last_checkpoint")
        try:
            with open(lc) as f:
                v = int(json.load(f)["version"])
            if v <= version:
                candidates.append(v)
        except (OSError, ValueError, KeyError):
            pass
        if not candidates:  # older checkpoints still serve time travel
            for fn in os.listdir(self.log_dir):
                if fn.endswith(".checkpoint.parquet"):
                    try:
                        v = int(fn.split(".")[0])
                    except ValueError:
                        continue
                    if v <= version:
                        candidates.append(v)
        for v in sorted(candidates, reverse=True):
            fp = os.path.join(self.log_dir, _checkpoint_name(v))
            try:
                t = pq.read_table(fp, columns=["add"])
            except Exception:
                continue
            adds = [a["path"] for a in t.column("add").to_pylist()
                    if a is not None and a.get("path")]
            return v, adds
        return None

    def checkpoint(self, version: Optional[int] = None) -> str:
        """Write a parquet checkpoint of the snapshot at `version` (default
        newest) + the `_last_checkpoint` pointer; returns the file path.
        Layout follows the Delta checkpoint shape: one row per action with
        nested `add` / `metaData` / `protocol` struct columns (other
        implementations read just the columns they need, as we do)."""
        v = self.version if version is None else version
        adds = [os.path.relpath(f, self.path) for f in self.active_files(v)]
        meta = self._snapshot_metadata(v)
        n = len(adds) + 2
        add_col = [None, None] + [
            {"path": p,
             "size": os.path.getsize(os.path.join(self.path, p)),
             "dataChange": False} for p in adds]
        meta_col = [None, meta] + [None] * len(adds)
        proto_col = [{"minReaderVersion": 1, "minWriterVersion": 2}] + \
            [None] * (n - 1)
        t = pa.table({
            "protocol": pa.array(proto_col),
            "metaData": pa.array(meta_col),
            "add": pa.array(add_col),
        })
        fp = os.path.join(self.log_dir, _checkpoint_name(v))
        pq.write_table(t, fp)
        with open(os.path.join(self.log_dir, "_last_checkpoint"),
                  "w") as f:
            json.dump({"version": v, "size": n}, f)
        return fp

    def _snapshot_metadata(self, version: int) -> dict:
        """Latest metaData action at or below `version` (full replay —
        only runs while writing a checkpoint)."""
        meta = {}
        for v in self._versions():
            if v > version:
                break
            with open(os.path.join(self.log_dir, _commit_name(v))) as f:
                for line in f:
                    act = json.loads(line)
                    if "metaData" in act:
                        meta = act["metaData"]
        return meta

    def history(self) -> List[dict]:
        out = []
        for v in self._versions():
            with open(os.path.join(self.log_dir, _commit_name(v))) as f:
                for line in f:
                    act = json.loads(line)
                    if "commitInfo" in act:
                        out.append({"version": v, **act["commitInfo"]})
        return out

    # ------------------------------------------------------------- reads
    def read(self, version: Optional[int] = None) -> pa.Table:
        files = self.active_files(version)
        if not files:
            first = pq.read_table(self.active_files(0)[0])
            return first.slice(0, 0)
        return pa.concat_tables([pq.read_table(f) for f in files])

    def to_df(self, version: Optional[int] = None):
        df = self.session.from_arrow(self.read(version), label="delta")
        # stable cross-query rescache identity: a delta version's content
        # is immutable, so (table root, version) keys the scan — two
        # to_df() calls at the same version share cache entries even
        # though each materializes a fresh arrow table, and a new commit
        # (version bump) re-keys everything downstream (invalidation by
        # construction)
        df.plan.fingerprint_token = (
            "delta", os.path.abspath(self.path),
            self.version if version is None else int(version))
        return df

    # ------------------------------------------------------------- DML
    def delete(self, condition: Expression) -> int:
        """DELETE FROM t WHERE condition; returns rows deleted. SQL DELETE
        semantics: only rows where the condition is TRUE go — a NULL
        condition keeps the row (hence the coalesce before negating)."""
        from ...expr import Coalesce, Not, lit
        snap_v = self.version
        before = self.read(snap_v)
        df = self.session.from_arrow(before, label="delta")
        kept = df.filter(Not(Coalesce(condition, lit(False)))).collect()
        self._rewrite(kept, op="DELETE", read_version=snap_v)
        return before.num_rows - kept.num_rows

    def update(self, set_exprs: Dict[str, Expression],
               condition: Expression = None) -> int:
        """UPDATE t SET col = expr [WHERE condition]; returns rows updated."""
        from ...expr import If, col
        snap_v = self.version
        current = self.read(snap_v)
        schema = current.schema
        unknown = set(set_exprs) - set(schema.names)
        if unknown:
            raise KeyError(f"UPDATE SET references non-existent column(s): "
                           f"{sorted(unknown)}")
        df = self.session.from_arrow(current, label="delta")
        projs = {}
        for name in schema.names:
            if name in set_exprs:
                new = set_exprs[name]
                if condition is not None:
                    new = If(condition, new, col(name))
                projs[name] = new
            else:
                projs[name] = col(name)
        if condition is None:
            out = df.select(**projs).collect()
            self._rewrite(out.cast(schema), op="UPDATE", read_version=snap_v)
            return out.num_rows
        # single pass: the match marker rides the same projection
        out = df.select(__upd=condition, **projs).collect()
        import pyarrow.compute as pc
        updated = int(pc.sum(pc.fill_null(out.column("__upd"), False))
                      .as_py() or 0)
        self._rewrite(out.select(schema.names).cast(schema), op="UPDATE",
                      read_version=snap_v)
        return updated

    def merge(self, source, on: Expression,
              when_matched_update: Optional[Dict[str, Expression]] = None,
              when_matched_delete: bool = False,
              when_not_matched_insert: Optional[Dict[str, Expression]]
              = None) -> dict:
        """MERGE INTO this table USING source ON on. Source columns inside
        `on` and the action expressions are referenced via src(name); target
        columns via col(name). Exactly one of update/delete may be given for
        the matched branch. Returns {"updated"/"deleted"/"inserted": counts}.

        Engine shape (GpuMergeIntoCommand analog): a LEFT condition join of
        target x prefixed-source computes the matched transform in one pass
        (after a multiple-match check — Delta's MERGE error), and an ANTI
        join computes the inserts; both ride the device plan."""
        from ...expr import Count, If, IsNotNull, Not, col, lit
        if when_matched_update and when_matched_delete:
            raise ValueError("choose update OR delete for the matched branch")
        snap_v = self.version
        current = self.read(snap_v)
        tgt_schema = current.schema
        names = list(tgt_schema.names)

        # source with prefixed columns (collision-free combined row), plus an
        # all-true marker so "matched" is detectable even when every source
        # column of a matched row is NULL (left-join null-fill vs data null)
        src_tbl = source.collect() if hasattr(source, "collect") else source
        src_prefixed = src_tbl.rename_columns(
            [_SRC_PREFIX + n for n in src_tbl.schema.names])
        probe_name = _SRC_PREFIX + "__matched"
        src_prefixed = src_prefixed.append_column(
            probe_name, pa.array([True] * src_tbl.num_rows,
                                 type=pa.bool_()))
        sdf = self.session.from_arrow(src_prefixed, label="merge-source")
        tdf = self.session.from_arrow(current, label="delta")

        # Delta error: a target row matched by multiple source rows is
        # ambiguous when a matched action exists
        if when_matched_update or when_matched_delete:
            j = tdf.join(sdf, how="inner", condition=on)
            # count matches per full target row via a synthetic key join is
            # overkill here: compare inner-join cardinality with the count of
            # DISTINCT matched target rows (existence join)
            n_pairs = j.agg(c=Count(lit(1))).collect().column("c")[0].as_py()
            ex = tdf.join(sdf, how="existence", condition=on)
            import pyarrow.compute as pc
            n_matched = int(pc.sum(pc.cast(
                ex.collect().column("exists"), pa.int64())).as_py() or 0)
            if n_pairs > n_matched:
                raise DeltaMultipleMatches(
                    "MERGE: a target row matched multiple source rows")
        else:
            n_matched = 0

        # matched transform: LEFT join keeps every target row exactly once
        # (the multiple-match check above guarantees <=1 source match). With
        # NO matched action the join is skipped entirely — an insert-only
        # MERGE must leave target rows untouched, and the left join would
        # duplicate a target row matched by multiple source rows (legal when
        # no matched clause exists).
        if when_matched_update or when_matched_delete:
            joined = tdf.join(sdf, how="left", condition=on)
            matched = IsNotNull(col(probe_name))
            projs = {}
            for name in names:
                if when_matched_update and name in when_matched_update:
                    projs[name] = If(matched, when_matched_update[name],
                                     col(name))
                else:
                    projs[name] = col(name)
            kept_df = self.session.from_arrow(
                joined.select(__m=matched, **projs).collect(), label="merge-t")
            if when_matched_delete:
                kept_df = kept_df.filter(Not(col("__m")))
            kept = kept_df.select(*names).collect()
        else:
            kept = current

        inserted = 0
        parts = [kept.cast(tgt_schema)]
        if when_not_matched_insert is not None:
            anti = sdf.join(tdf, how="anti", condition=_swap_sides(on))
            ins_projs = {n: when_not_matched_insert[n] for n in names}
            ins = anti.select(**ins_projs).collect()
            inserted = ins.num_rows
            parts.append(ins.cast(tgt_schema))
        result = pa.concat_tables(parts)
        self._rewrite(result, op="MERGE", read_version=snap_v)
        deleted = (n_matched if when_matched_delete else 0)
        return {"updated": n_matched if when_matched_update else 0,
                "deleted": deleted, "inserted": inserted}

    def optimize_zorder(self, columns, bits: int = 16,
                        curve: str = "zorder") -> dict:
        """OPTIMIZE ZORDER BY (reference `zorder/ZOrderRules.scala` +
        delta's OptimizeTableCommand): rows re-cluster by the morton key
        of the given columns (computed on the device engine) and the
        snapshot rewrites in that order, so later scans of range-filtered
        z columns touch fewer row groups (footer min/max tighten)."""
        from .zorder import CURVES, zorder_indices
        if curve not in CURVES:
            raise ValueError(f"unknown clustering curve {curve!r} "
                             f"(valid: {sorted(CURVES)})")
        columns = list(columns)  # consume a one-shot iterable ONCE
        if not columns:
            raise ValueError("OPTIMIZE ZORDER needs at least one column")
        snap_v = self.version
        t = self.read(snap_v)
        missing = [c for c in columns if c not in t.schema.names]
        if missing:
            raise ValueError(f"zorder columns not in table: {missing}")
        if t.num_rows:
            order = zorder_indices(self.session, t, columns, bits, curve)
            t = t.take(order)
        self._rewrite(t, op="OPTIMIZE", read_version=snap_v)
        return {"rows": t.num_rows, "zorder_by": columns, "curve": curve}

    # ------------------------------------------------------------- commit
    def _rewrite(self, table: pa.Table, op: str,
                 read_version: Optional[int] = None) -> None:
        """Full-rewrite transaction: remove the files of the snapshot the DML
        READ, add new parts, and stake read_version+1 — so a commit that
        landed between a DML's read and its write makes the O_EXCL stake
        fail with DeltaConcurrentModification instead of silently clobbering
        the interleaved commit (lost update)."""
        if read_version is None:
            read_version = self.version
        old = [os.path.relpath(f, self.path)
               for f in self.active_files(read_version)]
        fname = f"part-{uuid.uuid4().hex}.parquet"
        pq.write_table(table, os.path.join(self.path, fname))
        actions = [{"commitInfo": {"operation": op,
                                   "timestamp": int(time.time() * 1000)}}]
        actions += [{"remove": {"path": p, "dataChange": True}} for p in old]
        actions.append({"add": {"path": fname, "size": os.path.getsize(
            os.path.join(self.path, fname)), "dataChange": True}})
        _write_commit(self.log_dir, read_version + 1, actions)
        self._maybe_checkpoint(read_version + 1)

    def _maybe_checkpoint(self, version: int) -> None:
        """Delta's periodic checkpointing: every checkpointInterval-th
        commit consolidates the snapshot into a parquet checkpoint."""
        from ...config import get_default_conf
        try:
            conf = self.session.conf if self.session is not None \
                else get_default_conf()
            interval = int(conf.get(
                "spark.rapids.delta.checkpointInterval"))
        except Exception:
            interval = 10
        if interval > 0 and version > 0 and version % interval == 0:
            try:
                self.checkpoint(version)
            except Exception:
                # best-effort, like Delta: the DML's commit already landed;
                # a failed checkpoint must not make it look failed (the
                # JSON log remains fully replayable without it)
                pass


def _commit_name(v: int) -> str:
    return f"{v:010d}.json"


def _checkpoint_name(v: int) -> str:
    return f"{v:010d}.checkpoint.parquet"


def _write_commit(log_dir: str, version: int, actions: List[dict]) -> None:
    """Optimistic commit: O_EXCL stake on the version file."""
    path = os.path.join(log_dir, _commit_name(version))
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        raise DeltaConcurrentModification(
            f"version {version} was committed concurrently")
    with os.fdopen(fd, "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")


def _swap_sides(on: Expression) -> Expression:
    """Rewrite the ON condition for the insert anti-join, where the SOURCE is
    the left (probe) side: src(x) stays src-prefixed (now a left column) and
    bare target refs stay bare (now right columns) — names are disjoint, so
    the expression itself is reusable as-is."""
    return on
