"""Ecosystem datasources (reference L8: delta-lake/, iceberg/)."""
